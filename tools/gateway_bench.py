"""Gateway-path TTFT benchmark: the full serving path the north star
measures (BASELINE.md: p50 gateway TTFT < 200 ms) — websocket chat gateway
→ questions topic → ai-chat-completions on the TPU engine → streamed chunks
back through the consume side of the chat socket.

Requests arrive on a Poisson process at a configurable fraction of engine
capacity (sub-saturation — the regime the target is defined in; the r2
bench's 4.3 s "TTFT" was a saturated-queue artifact). TTFT is measured at
the CLIENT: time from sending the question on the socket to the first
streamed chunk arriving on it, including gateway hops and broker transport.

Parity anchor: ``ChatCompletionsStep.java:151`` (streaming chunk path),
``examples/applications/openai-completions/pipeline.yaml:40-49``.
"""

from __future__ import annotations

import asyncio
import json
import random
import socket
import time
from typing import Any

PIPELINE = """
topics:
  - name: "questions-topic"
    creation-mode: create-if-not-exists
  - name: "answers-topic"
    creation-mode: create-if-not-exists
  - name: "stream-topic"
    creation-mode: create-if-not-exists
pipeline:
  - name: "chat"
    type: "ai-chat-completions"
    input: "questions-topic"
    output: "answers-topic"
    configuration:
      completion-field: "value.answer"
      stream-to-topic: "stream-topic"
      stream-response-completion-field: "value"
      min-chunks-per-message: 4
      max-tokens: %MAX_TOKENS%
      messages:
        - role: user
          content: "{{ value.question }}"
"""

CONFIGURATION = """
configuration:
  resources:
    - type: "tpu-serving-configuration"
      name: "tpu"
      configuration:
%SERVING%
"""

GATEWAYS = """
gateways:
  - id: "chat"
    type: chat
    chat-options:
      questions-topic: "questions-topic"
      answers-topic: "stream-topic"
      headers:
        - key: "langstream-client-session-id"
          value-from-parameters: sessionId
"""

INSTANCE = """
instance:
  streamingCluster:
    type: memory
"""

# the streaming phase wants one gateway frame per decode chunk — chunk
# batching would average the very inter-frame intervals it measures
STREAM_PIPELINE = PIPELINE.replace(
    "min-chunks-per-message: 4", "min-chunks-per-message: 1"
)


def _pct(sorted_values, q: float):
    """Nearest-rank percentile of an already-sorted list (None when
    empty) — the ONE helper every phase quantiles with, so the rounding
    semantics can never drift between phases."""
    if not sorted_values:
        return None
    return sorted_values[
        min(len(sorted_values) - 1, int(q * len(sorted_values)))
    ]


def _yaml_serving(serving: dict[str, Any]) -> str:
    return "\n".join(
        f"        {key}: {json.dumps(value)}"
        for key, value in serving.items()
        if value is not None
    )


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


async def run_gateway_bench(
    serving: dict[str, Any],
    *,
    prompt: str,
    max_tokens: int = 48,
    requests: int = 64,
    warmup: int = 6,
    arrival_rate_hz: float = 4.0,
    seed: int = 7,
    instance_yaml: str | None = None,
) -> dict[str, Any]:
    """Returns {"gateway_ttft_p50_s", "gateway_ttft_p99_s", "e2e_p50_s",
    "arrival_rate_hz", "requests"}.

    ``instance_yaml`` overrides the streaming cluster (default: the memory
    broker) — ``BENCH_BROKER=tsb`` routes the whole chat path through a
    real tsbroker process so a recorded perf number includes a real broker
    transport."""
    import aiohttp

    from langstream_tpu.controlplane.server import (
        ControlPlaneServer,
        LocalComputeRuntime,
    )
    from langstream_tpu.controlplane.stores import InMemoryApplicationStore
    from langstream_tpu.gateway.server import GatewayRegistry, GatewayServer

    registry = GatewayRegistry()
    compute = LocalComputeRuntime(gateway_registry=registry)
    control = ControlPlaneServer(
        store=InMemoryApplicationStore(), compute=compute, port=_free_port()
    )
    gateway = GatewayServer(registry=registry, port=_free_port())
    await control.start()
    await gateway.start()
    session = aiohttp.ClientSession()
    try:
        api = f"http://127.0.0.1:{control.port}"
        async with session.put(f"{api}/api/tenants/bench") as resp:
            assert resp.status in (200, 201), await resp.text()
        payload = {
            "files": {
                "pipeline.yaml": PIPELINE.replace(
                    "%MAX_TOKENS%", str(max_tokens)
                ),
                "configuration.yaml": CONFIGURATION.replace(
                    "%SERVING%", _yaml_serving(serving)
                ),
                "gateways.yaml": GATEWAYS,
            },
            "instance": instance_yaml or INSTANCE,
        }
        async with session.post(
            f"{api}/api/applications/bench/chatapp", json=payload
        ) as resp:
            assert resp.status in (200, 201), await resp.text()

        ws_base = f"ws://127.0.0.1:{gateway.port}"

        async def one_request(i: int) -> dict[str, float]:
            url = f"{ws_base}/v1/chat/bench/chatapp/chat?param:sessionId=s{i}"
            async with session.ws_connect(url) as chat:
                t0 = time.monotonic()
                await chat.send_json({"value": {"question": prompt}})
                ttft = None
                while True:
                    msg = await asyncio.wait_for(chat.receive_json(), 600)
                    # ack for the produce; pushes carry the streamed chunks
                    if "record" not in msg:
                        continue
                    if ttft is None:
                        ttft = time.monotonic() - t0
                    headers = (msg.get("record") or {}).get("headers") or {}
                    if headers.get("stream-last-message") in ("true", True):
                        return {
                            "ttft": ttft,
                            "e2e": time.monotonic() - t0,
                        }

        from langstream_tpu.serving.engine import TpuServingEngine

        # warmup compiles prefill + decode variants: sequential requests
        # cover the light-load regime (and the engine's own warmup-on-start
        # wave, when configured), then a concurrent wave drives the active
        # slot count past the light threshold so the heavy-chunk burst and
        # padded prefill batches compile BEFORE measurement — a first
        # compile landing mid-run convoys every queued request behind it
        for i in range(warmup):
            await one_request(10_000 + i)
        if warmup > 0:
            wave = min(int(serving.get("slots", 8) or 8), 16)
            await asyncio.gather(
                *(one_request(20_000 + i) for i in range(wave))
            )

        # drop warmup requests from the engine-side timing samples so the
        # TTFT decomposition below covers only the measured window — and
        # from the journey ledger, which decomposes the same window per
        # request (serving/journey.py)
        from langstream_tpu.serving.journey import (
            JOURNEYS,
            segments as journey_segments,
        )

        with TpuServingEngine._instances_lock:
            engines = list(TpuServingEngine._instances.values())
        for engine in engines:
            engine.request_timings.clear()
        JOURNEYS.clear()

        rng = random.Random(seed)
        tasks: list[asyncio.Task] = []
        for i in range(requests):
            tasks.append(asyncio.ensure_future(one_request(i)))
            await asyncio.sleep(rng.expovariate(arrival_rate_hz))
        samples = await asyncio.gather(*tasks)
        ttfts = sorted(s["ttft"] for s in samples)
        e2es = sorted(s["e2e"] for s in samples)

        pct = _pct

        out = {
            "gateway_ttft_p50_s": round(pct(ttfts, 0.50), 4),
            "gateway_ttft_p99_s": round(pct(ttfts, 0.99), 4),
            "e2e_p50_s": round(pct(e2es, 0.50), 4),
            "arrival_rate_hz": arrival_rate_hz,
            "requests": requests,
        }
        # TTFT decomposition from the engine's per-request timestamps:
        # queue-wait (enqueue → slot admission), prefill (admission → first
        # token), first-chunk (everything after the engine emitted the
        # first token: stream adapter, broker hop, gateway push — the
        # client-measured p50 minus the engine-measured p50). A p50 16x
        # over target now names its component instead of one opaque number.
        # Re-snapshot _instances: with warmup=0 the engine is only lazily
        # created during the measured window, after the snapshot above.
        with TpuServingEngine._instances_lock:
            engines = list(TpuServingEngine._instances.values())
        timings = [t for e in engines for t in list(e.request_timings)]
        if timings:
            queue_waits = sorted(t["queue_wait"] for t in timings)
            prefills = sorted(t["prefill"] for t in timings)
            engine_ttfts = sorted(t["ttft"] for t in timings)
            out.update({
                "queue_wait_p50_s": round(pct(queue_waits, 0.50), 4),
                "queue_wait_p99_s": round(pct(queue_waits, 0.99), 4),
                "prefill_p50_s": round(pct(prefills, 0.50), 4),
                "engine_ttft_p50_s": round(pct(engine_ttfts, 0.50), 4),
                "first_chunk_p50_s": round(
                    max(0.0, pct(ttfts, 0.50) - pct(engine_ttfts, 0.50)), 4
                ),
            })
        # per-request journey segments (serving/journey.py): the same
        # TTFT decomposition as above, but per REQUEST and per lifecycle
        # edge — queue vs prefill vs (under split pools) transfer vs
        # decode-admission vs first-step — the instrument the split-pool
        # bench round compares against the combined baseline. Segments
        # absent from this run's topology (no handoffs on a combined
        # fleet) simply don't appear; perf_diff reports that as coverage
        # drift, never a regression.
        seg_samples: dict[str, list[float]] = {}
        for jid in JOURNEYS.ids():
            for seg in journey_segments(JOURNEYS.events(jid)):
                seg_samples.setdefault(seg["segment"], []).append(
                    seg["ms"] / 1000.0
                )
        journey_out: dict[str, Any] = {}
        for name in (
            "ingest", "queue", "prefix-hydrate", "adapter-hydrate",
            "prefill", "export",
            "handoff-wait", "transfer", "decode-admission", "first-step",
            "decode",
        ):
            values = sorted(seg_samples.get(name) or [])
            if values:
                journey_out[name] = {
                    "p50_s": round(pct(values, 0.50), 4),
                    "p99_s": round(pct(values, 0.99), 4),
                    "n": len(values),
                }
        if journey_out:
            out["journey_segments"] = journey_out
        # decode roofline: the HBM-bandwidth floor for one decode step at
        # this engine shape (profiling.decode_step_bytes), so a recorded
        # tok/s number carries its achieved-vs-possible context. Achieved
        # step time comes from the ENGINE-side decode phase over the
        # actual per-request step count — EOS can end generation well
        # before max_tokens, so dividing a client-side window by the token
        # budget would overstate utilization (even past 1.0).
        if engines and max_tokens > 1:
            from langstream_tpu.serving.profiling import decode_step_bytes

            engine = engines[0]
            cfg = engine.config
            try:
                window = (
                    engine._window_for(cfg.max_seq_len) or cfg.max_seq_len
                )
                roofline = decode_step_bytes(
                    engine.model_config,
                    slots=cfg.slots,
                    window=window,
                    quantize=cfg.quantize,
                    kv_dtype_bytes=4 if cfg.model_dtype == "float32" else 2,
                    kv_quantize=cfg.kv_quantize,
                )
            except Exception as e:
                # shapes the roofline model doesn't cover (MoE trees):
                # the bench result simply omits the roofline keys
                print(f"roofline unavailable for this model: {e}")
                roofline = None
            step_ms = sorted(
                t["decode"] / (t["tokens"] - 1) * 1000.0
                for t in timings
                if t.get("tokens", 0) > 1
            )
            if roofline is not None and step_ms:
                achieved_ms = pct(step_ms, 0.50)
                out.update({
                    "roofline_min_step_ms": round(roofline.min_step_ms(), 4),
                    "achieved_step_ms_p50": round(achieved_ms, 4),
                    "hbm_utilization": round(
                        roofline.utilization(achieved_ms), 4
                    ),
                    # which roof: detected generation + physical HBM (null
                    # off-TPU or when the plugin hides memory stats)
                    "hbm_generation": roofline.generation,
                    "hbm_bytes": roofline.hbm_bytes,
                })
        # flight-recorder rollup: attributes the TTFT gap — was the engine
        # stalled (and why), paying host overhead, or convoyed behind a
        # recompile — so BENCH can name the component instead of re-guessing
        if engines:
            from langstream_tpu.serving.flight import bench_rollup

            # the engine this bench configured; fall back to the first
            # live one, and record when other engines were present so a
            # single-engine rollup is never mistaken for the whole process
            chat_engine = next(
                (e for e in engines if e.config.model == serving.get("model")),
                engines[0],
            )
            out["flight"] = bench_rollup(chat_engine.flight.summary())
            if len(engines) > 1:
                out["flight"]["engines_observed"] = len(engines)
                out["flight"]["model"] = chat_engine.config.model
        return out
    finally:
        await session.close()
        await gateway.stop()
        await control.stop()
        await compute.close()


async def run_stream_phase(
    *,
    serving: dict[str, Any] | None = None,
    streams: int = 8,
    disconnects: int = 3,
    max_tokens: int = 32,
    warmup: int = 2,
    prompt: str = "please stream the full fleet status report",
    instance_yaml: str | None = None,
) -> dict[str, Any]:
    """Streaming-delivery phase (docs/OBSERVABILITY.md Streaming): N
    concurrent streaming WS clients against the in-process gateway +
    TBT-instrumented engine (``streaming: true``, one frame per decode
    chunk), measuring the SLO surface the tbt plane alerts on —
    client-observed time-between-frames p50/p99/max per priority class,
    first-frame TTFB, engine-side stall count — then a mid-stream
    disconnect burst whose verdict is the cancellation ledger:
    ``slots_reclaimed_on_disconnect`` (every disconnected stream's
    decode slot freed at a chunk boundary, ``stream-cancel`` logged with
    its wasted-token bill) — the zero-silent-loss shape of the streaming
    plane. ``perf_diff`` declares the worse-directions so a regression
    that stretches TBT, stalls streams, or leaks cancelled slots is
    flagged, not averaged away."""
    import aiohttp

    from langstream_tpu.controlplane.server import (
        ControlPlaneServer,
        LocalComputeRuntime,
    )
    from langstream_tpu.controlplane.stores import InMemoryApplicationStore
    from langstream_tpu.gateway.server import GatewayRegistry, GatewayServer
    from langstream_tpu.serving.engine import TpuServingEngine

    serving = dict(serving or {})
    serving.setdefault("model", "tiny")
    serving.setdefault("slots", 4)
    serving.setdefault("max-seq-len", 256)
    serving.setdefault("decode-chunk", 4)
    serving.setdefault("model-dtype", "float32")
    serving.setdefault("streaming", True)

    registry = GatewayRegistry()
    compute = LocalComputeRuntime(gateway_registry=registry)
    control = ControlPlaneServer(
        store=InMemoryApplicationStore(), compute=compute, port=_free_port()
    )
    gateway = GatewayServer(registry=registry, port=_free_port())
    await control.start()
    await gateway.start()
    session = aiohttp.ClientSession()
    t_start = time.monotonic()
    try:
        api = f"http://127.0.0.1:{control.port}"
        async with session.put(f"{api}/api/tenants/bench") as resp:
            assert resp.status in (200, 201), await resp.text()
        payload = {
            "files": {
                "pipeline.yaml": STREAM_PIPELINE.replace(
                    "%MAX_TOKENS%", str(max_tokens)
                ),
                "configuration.yaml": CONFIGURATION.replace(
                    "%SERVING%", _yaml_serving(serving)
                ),
                "gateways.yaml": GATEWAYS,
            },
            "instance": instance_yaml or INSTANCE,
        }
        async with session.post(
            f"{api}/api/applications/bench/streamapp", json=payload
        ) as resp:
            assert resp.status in (200, 201), await resp.text()

        ws_base = f"ws://127.0.0.1:{gateway.port}"

        async def one_stream(
            i: int, priority: str = "default", disconnect_after: int = 0
        ) -> dict[str, Any]:
            # option:streaming stamps the per-message stream-id header
            # the engine registers its future under (disconnect →
            # cancel); param:priority keys the per-class TBT digests
            url = (
                f"{ws_base}/v1/chat/bench/streamapp/chat"
                f"?param:sessionId=s{i}&option:streaming=true"
                f"&param:priority={priority}"
            )
            out: dict[str, Any] = {
                "frames": 0, "intervals": [], "priority": priority,
            }
            async with session.ws_connect(url) as chat:
                t0 = time.monotonic()
                await chat.send_json({"value": {"question": f"{prompt} #{i}"}})
                last_t = None
                while True:
                    msg = await asyncio.wait_for(chat.receive_json(), 600)
                    if "record" not in msg:
                        continue  # the produce ack; frames are pushes
                    now = time.monotonic()
                    out["frames"] += 1
                    if last_t is None:
                        out["ttfb"] = now - t0
                    else:
                        out["intervals"].append(now - last_t)
                    last_t = now
                    if disconnect_after and out["frames"] >= disconnect_after:
                        # leave mid-generation: the async-with teardown
                        # closes the socket, the gateway cancels the
                        # stream-key, the engine frees the slot at the
                        # next chunk boundary
                        out["disconnected"] = True
                        return out
                    headers = (msg.get("record") or {}).get("headers") or {}
                    if headers.get("stream-last-message") in ("true", True):
                        out["e2e"] = now - t0
                        return out

        # warmup compiles prefill + decode variants (sequential, then a
        # small concurrent wave) so no measured TBT interval carries an
        # XLA compile inside it
        for i in range(warmup):
            await one_stream(10_000 + i)
        if warmup > 0:
            wave = min(int(serving.get("slots", 4) or 4), 8)
            await asyncio.gather(
                *(one_stream(20_000 + i) for i in range(wave))
            )

        with TpuServingEngine._instances_lock:
            engines = list(TpuServingEngine._instances.values())
        assert engines, "no engine came up behind the streaming gateway"
        engine = engines[0]
        engine.request_timings.clear()
        base = dict(engine.stats().get("streaming") or {})

        # ---- measured wave: mixed priority classes -------------------
        classes = ("interactive", "default")
        results = await asyncio.gather(
            *(
                one_stream(i, priority=classes[i % len(classes)])
                for i in range(streams)
            )
        )

        # ---- disconnect burst: leave after the first frame -----------
        burst = await asyncio.gather(
            *(
                one_stream(50_000 + i, disconnect_after=1)
                for i in range(disconnects)
            )
        )
        # the cancel lands via the gateway's socket-teardown sweep and
        # the engine observes it at the next chunk boundary: wait the
        # ledger out instead of racing it
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline:
            now_s = engine.stats().get("streaming") or {}
            if (
                now_s.get("reclaimed", 0) - base.get("reclaimed", 0)
                >= disconnects
            ):
                break
            await asyncio.sleep(0.05)

        streaming_now = dict(engine.stats().get("streaming") or {})
        cancel_events = [
            e
            for e in engine.flight.recent_events(0)
            if e["kind"] == "stream-cancel"
        ]

        pct = _pct
        ttfbs = sorted(r["ttfb"] for r in results if "ttfb" in r)
        intervals_by_class: dict[str, list[float]] = {}
        all_intervals: list[float] = []
        for r in results:
            intervals_by_class.setdefault(r["priority"], []).extend(
                r["intervals"]
            )
            all_intervals.extend(r["intervals"])
        all_intervals.sort()
        frames = sorted(r["frames"] for r in results)
        cancelled = streaming_now.get("cancelled", 0) - base.get(
            "cancelled", 0
        )
        reclaimed = streaming_now.get("reclaimed", 0) - base.get(
            "reclaimed", 0
        )
        out: dict[str, Any] = {
            "streams": streams,
            "disconnects": disconnects,
            "max_tokens": max_tokens,
            # client-observed: the ONLY vantage the SLO is defined at —
            # engine emit → broker hop → gateway push all inside it
            "gateway_stream_ttfb_s": round(pct(ttfbs, 0.50), 4),
            "gateway_stream_tbt_p50_s": round(pct(all_intervals, 0.50), 4),
            "gateway_stream_tbt_p99_s": round(pct(all_intervals, 0.99), 4),
            "gateway_stream_tbt_max_s": round(all_intervals[-1], 4)
            if all_intervals
            else None,
            "gateway_stream_frames_min": frames[0] if frames else 0,
            # the byte-identity acceptance rides on ≥2 incremental frames
            "multi_frame": bool(frames) and frames[0] >= 2,
            "tbt_by_class": {
                name: {
                    "p50_s": round(pct(sorted(vals), 0.50), 4),
                    "p99_s": round(pct(sorted(vals), 0.99), 4),
                    "max_s": round(max(vals), 4),
                    "n": len(vals),
                }
                for name, vals in sorted(intervals_by_class.items())
                if vals
            },
            # engine-side per-class digests (the stats()["streaming"]
            # surface): client TBT minus this is the transport share
            "engine_tbt_by_class": streaming_now.get("tbt") or {},
            "gateway_stream_stalls": streaming_now.get("stalls", 0)
            - base.get("stalls", 0),
            # the cancellation ledger (zero-silent-loss shape): every
            # disconnected stream cancelled AND its decode slot freed
            "gateway_stream_cancelled": cancelled,
            "gateway_stream_reclaimed": reclaimed,
            "gateway_stream_cancel_reclaim_fraction": round(
                reclaimed / disconnects, 4
            )
            if disconnects
            else None,
            "slots_reclaimed_on_disconnect": reclaimed >= disconnects,
            "gateway_stream_tokens_wasted": sum(
                int(e.get("tokens_wasted") or 0) for e in cancel_events
            ),
            "stream_cancel_events": len(cancel_events),
            "disconnected_streams": sum(
                1 for r in burst if r.get("disconnected")
            ),
            "wall_s": round(time.monotonic() - t_start, 3),
        }
        return out
    finally:
        await session.close()
        await gateway.stop()
        await control.stop()
        await compute.close()


async def run_warm_prefix_phase(
    *,
    serving: dict[str, Any] | None = None,
    tenants: int = 8,
    repeats: int = 2,
    system_chars: int = 640,
    max_tokens: int = 8,
    t2_dir: str | None = None,
) -> dict[str, Any]:
    """Warm-prefix phase for the tiered prefix store (docs/PREFIX.md):
    N tenants share one long system prompt across TWO replicas of the
    same fleet, routed by prefix affinity.

    Replica A takes the flood first (tenant prompts differ only in
    their short question suffix), so its T0 cache fills, the byte
    budgets demote the shared blocks T0→T1→T2, and the router pins the
    prompt's prefix digest to A. Then A drains and replica B — sharing
    only the T2 object store — serves the same prefix: its first
    request HYDRATES (T2→T1→T0) instead of recomputing, and the bench
    records the per-tier hit counts, the router's prefix counters, the
    ``prefix-hydrate`` journey segment, and cold-compute vs hydrated
    TTFT. Runs the engines in-process over a shared local-disk T2 —
    the cross-replica path without a second host."""
    import tempfile

    from langstream_tpu.gateway.router import ReplicaRouter
    from langstream_tpu.serving.engine import ServingConfig, TpuServingEngine
    from langstream_tpu.serving.journey import (
        JOURNEYS,
        segments as journey_segments,
    )
    from langstream_tpu.serving.prefixstore import (
        PrefixStoreSpec,
        prefix_digest_for_text,
    )

    t2_dir = t2_dir or tempfile.mkdtemp(prefix="bench_prefix_t2_")
    serving = dict(serving or {})
    serving.setdefault("model", "tiny")
    serving.setdefault("slots", 4)
    serving.setdefault("max-seq-len", 1024)
    serving.setdefault("decode-chunk", 8)
    serving.setdefault("model-dtype", "float32")
    serving.setdefault("kv-layout", "paged")
    serving.setdefault("kv-block-size", 32)
    serving.setdefault("prefix-cache", True)
    # tight tier budgets so the shared blocks cascade to T2 within the
    # phase instead of needing HBM pressure: T0 keeps ~4 blocks, T1 is
    # pass-through (every demotion reaches object storage)
    serving["prefix-store"] = {
        "t0-bytes": None,  # per-replica below (A demotes, B may keep)
        "t1-bytes": 1,
        "t2": {"type": "local", "path": t2_dir},
        "hydrate-timeout-s": 10.0,
        "t2-rescan-s": 0.2,
    }

    def _config(t0_bytes: int | None) -> ServingConfig:
        # both replicas run with a zero T0 budget so shared blocks
        # demote promptly (and the warmup can exercise the hydrate
        # path on each side before anything is measured)
        spec = dict(serving["prefix-store"], **{"t0-bytes": t0_bytes})
        d = dict(serving)
        d["prefix-store"] = spec
        return ServingConfig.from_dict(d)

    system = ("All agents must follow the fleet prompt contract. " * 40)[
        :system_chars
    ]
    digest = prefix_digest_for_text(system)
    # a long freshness window: this phase drives the router directly
    # between compile-heavy generates, and a production poller would be
    # re-observing continuously — a stale pick here would only measure
    # the rig's compile time, not the routing semantics under test
    router = ReplicaRouter(fresh_s=3600.0)

    def _observe(a_draining: bool = False) -> None:
        router.observe([
            {
                "replica": "bench-ai-0", "queued": 0, "occupancy": 0,
                "slots": 4, "draining": a_draining,
            },
            {"replica": "bench-ai-1", "queued": 0, "occupancy": 0,
             "slots": 4},
        ])

    _observe()

    engine_a = TpuServingEngine(_config(0))
    replica_names = {"bench-ai-0": engine_a}
    ttfts: list[float] = []
    cold_ttft = None
    picks: dict[str, int] = {}

    async def _ask(engine, tenant_i: int) -> float:
        prompt = f"{system}\nTenant {tenant_i}: what is the fleet status?"
        result = await engine.generate(
            prompt, {"max-tokens": max_tokens, "temperature": 0}
        )
        return float(result["ttft"])

    async def _drain_store(engine, rounds: int) -> None:
        # wait the demotion cascade out: the chain unwinds leaf-first,
        # so the head digest — the one a cold replica must find first —
        # reaches object storage last
        for _ in range(rounds):
            st = engine.stats()["prefixstore"]
            if (
                st["t0"]["blocks"] == 0
                and st["t1"]["entries"] == 0
                and not st["t2"]["in_transit_bytes"]
                and not st["t2"]["pending_jobs"]
            ):
                return
            await asyncio.sleep(0.02)

    async def _warm_variants(engine, who: str) -> None:
        # compile BOTH prefill paths before any measured request — the
        # full prefill (cold-compute baseline) and the prefix
        # continuation (warm/hydrated requests) are differently-shaped
        # XLA programs, and a first compile landing inside a measured
        # TTFT would drown the tier effect it measures. The text is
        # replica-UNIQUE from its FIRST character (a shared leading
        # block would hydrate from T2 and skip the full-prefill compile
        # the cold baseline needs warmed), and slightly LONGER than the
        # measured system prompt so the continuation request's reused-
        # prefix window lands in the same read-blocks bucket as the
        # measured warm/hydrated requests.
        warm = (f"{who} variant warmup preamble, shared with no one. "
                * 40)[: system_chars + 48]
        first = f"{warm}\nTenant w: first?"
        await engine.generate(first, {"max-tokens": 2, "temperature": 0})
        await engine.generate(
            f"{warm}\nTenant w: again, reusing the cached prefix?",
            {"max-tokens": 2, "temperature": 0},
        )
        if engine.prefix_store.spec.t0_bytes == 0:
            # a zero T0 budget demotes the warmup chain to T2; one more
            # request on it then exercises hydrate → promote, compiling
            # the fetch/scatter programs the measured requests reuse
            await _drain_store(engine, 600)
            # the EXACT first prompt again: its whole registered chain
            # hydrates, so the continuation variant this compiles has
            # the same short-suffix bucket the measured repeats use
            await engine.generate(first, {"max-tokens": 2, "temperature": 0})
            # the promoted blocks now re-demote (t0-bytes=0): wait the
            # cascade out so ITS first gather/serialize compiles land
            # here, not inside a measured request's admission pass
            await _drain_store(engine, 600)

    await _warm_variants(engine_a, "replica-a")
    for r in range(repeats):
        for i in range(tenants):
            target = router.pick(f"tenant-{i}", prefix=digest)
            picks[target] = picks.get(target, 0) + 1
            ttft = await _ask(replica_names[target], i)
            if cold_ttft is None:
                cold_ttft = ttft
            else:
                ttfts.append(ttft)
    # let the demotion cascade drain FULLY to object storage before A
    # goes away (see _drain_store: the head digest lands last)
    await _drain_store(engine_a, 3000)
    stats_a = engine_a.stats()["prefixstore"]
    router_mid = dict(router.stats())
    await engine_a.close()
    TpuServingEngine.reset_instances()

    # replica B: same fleet, fresh HBM, shared T2. A is draining, so
    # the router breaks the prefix pin and re-pins onto B.
    engine_b = TpuServingEngine(_config(0))
    engine_b.prefix_store.flush(10.0)
    _observe(a_draining=True)
    await _warm_variants(engine_b, "replica-b")
    # cold-compute baseline on B: an equally long prompt that shares NO
    # prefix with anything in the tiers
    baseline_prompt = ("Entirely different preamble with no shared head. "
                       * 40)[:system_chars]
    cold_compute = float(
        (
            await engine_b.generate(
                f"{baseline_prompt}\nTenant x: what is the fleet status?",
                {"max-tokens": max_tokens, "temperature": 0},
            )
        )["ttft"]
    )
    JOURNEYS.clear()
    target = router.pick("tenant-0", prefix=digest)
    assert target == "bench-ai-1", target
    hydrated_ttft = await _ask(engine_b, 0)
    # repeat traffic (any tenant) now follows the prefix pin back to B
    repeat_target = router.pick("tenant-3", prefix=digest)
    stats_b = engine_b.stats()["prefixstore"]
    seg_samples: list[float] = []
    for jid in JOURNEYS.ids():
        for seg in journey_segments(JOURNEYS.events(jid)):
            if seg["segment"] == "prefix-hydrate":
                seg_samples.append(seg["ms"] / 1000.0)
    await engine_b.close()
    TpuServingEngine.reset_instances()

    ttfts.sort()
    pct = _pct

    out: dict[str, Any] = {
        "tenants": tenants,
        "repeats": repeats,
        "system_chars": system_chars,
        "prefix_cold_ttft_s": round(cold_ttft or 0.0, 4),
        "prefix_warm_ttft_p50_s": round(pct(ttfts, 0.50), 4) if ttfts else None,
        "prefix_warm_ttft_p99_s": round(pct(ttfts, 0.99), 4) if ttfts else None,
        # replica B: hydrate-vs-recompute, the cross-replica headline
        "cold_compute_ttft_s": round(cold_compute, 4),
        "prefix_hydrate_ttft_s": round(hydrated_ttft, 4),
        "prefix_hydrate_speedup": round(
            cold_compute / hydrated_ttft, 3
        ) if hydrated_ttft > 0 else None,
        "tier_hits": {
            "t0_warm_hits": stats_a["t0"]["hits"],
            "t1_promotions_b": stats_b["t1"]["hits"],
            "t2_hydrations_b": stats_b["hydrations"],
        },
        "replica_a": {
            "demotions_t0_t1": stats_a["demotions_t0_t1"],
            "demotions_t1_t2": stats_a["demotions_t1_t2"],
            "t2_entries": stats_a["t2"]["entries"],
            "ledger": stats_a["ledger"],
        },
        "replica_b": {
            "hydrations": stats_b["hydrations"],
            "promotions": stats_b["promotions"],
            "hydrate_failures": stats_b["hydrate_failures"],
            "ledger": stats_b["ledger"],
        },
        "router": {
            "prefix_hits": router.stats()["prefix_hits"],
            "prefix_rerouted": router.stats()["prefix_rerouted"],
            "pinned_prefixes": router.stats()["pinned_prefixes"],
            "warm_phase_prefix_hits": router_mid["prefix_hits"],
            "repeat_followed_pin": repeat_target == "bench-ai-1",
            "picks_by_replica": picks,
        },
    }
    if seg_samples:
        seg_samples.sort()
        out["journey_segments"] = {
            "prefix-hydrate": {
                "p50_s": round(pct(seg_samples, 0.50), 4),
                "p99_s": round(pct(seg_samples, 0.99), 4),
                "n": len(seg_samples),
            }
        }
    return out


async def run_multi_lora_phase(
    *,
    serving: dict[str, Any] | None = None,
    tenants: int = 6,
    adapters: int = 4,
    repeats: int = 3,
    max_tokens: int = 8,
    t2_dir: str | None = None,
) -> dict[str, Any]:
    """Multi-LoRA phase for the tiered adapter store (docs/ADAPTERS.md):
    N tenants spread over M named adapters with M > the device budget
    (``t0-entries``), so heterogeneous-adapter traffic churns the T0
    row LRU — load, evict, re-load — while half the fleet is ONLY
    published to the T2 origin and first-touches take the hydration
    path a cross-replica cold start takes.

    Records warm vs hydrate TTFT quantiles, the T0 hit ratio, eviction
    churn, the ``adapter-hydrate`` journey segment, the router's
    adapter-affinity counters, and the store's exact byte ledger with
    its conservation verdict (``t1 + in_transit + t2 == inserted +
    discovered - evicted``). ``perf_diff`` declares the worse-directions
    (TTFT p99 up, hit ratio down, evictions up) so adapter-plane
    regressions are flagged, not averaged away."""
    import tempfile

    from langstream_tpu.gateway.router import ReplicaRouter
    from langstream_tpu.serving.adapters import (
        make_lora_arrays,
        publish_adapter,
    )
    from langstream_tpu.serving.engine import ServingConfig, TpuServingEngine
    from langstream_tpu.serving.journey import (
        JOURNEYS,
        segments as journey_segments,
    )

    t2_dir = t2_dir or tempfile.mkdtemp(prefix="bench_lora_t2_")
    serving = dict(serving or {})
    serving.setdefault("model", "tiny")
    serving.setdefault("slots", 4)
    serving.setdefault("max-seq-len", 256)
    serving.setdefault("decode-chunk", 4)
    serving.setdefault("model-dtype", "float32")
    serving.setdefault("kv-layout", "paged")
    serving.setdefault("kv-block-size", 16)
    t0_entries = max(2, adapters - 2)
    serving["adapter-store"] = {
        "rank": 4,
        # fewer device rows than adapters: the churn under test
        "t0-entries": t0_entries,
        "t1-bytes": 64 << 20,
        "t2": {"type": "local", "path": t2_dir},
        "hydrate-timeout-s": 10.0,
        "t2-rescan-s": 0.2,
    }
    config = ServingConfig.from_dict(serving)
    engine = TpuServingEngine(config)
    store = engine.adapter_store
    mc = engine.model_config
    fingerprint = engine.adapter_fingerprint()
    rank = config.adapter_store.rank
    names = [f"bench-lora-{m}" for m in range(adapters)]
    # even adapters install locally (T1); odd ones are published ONLY
    # to the shared T2 origin, as another replica (or an offline
    # publisher) would — their first touch exercises discover + hydrate
    published = []
    for m, name in enumerate(names):
        arrays = make_lora_arrays(
            layers=mc.layers, hidden=mc.hidden, heads=mc.heads,
            kv_heads=mc.kv_heads, head_dim=mc.head_dim, rank=rank,
            seed=101 + m,
        )
        if m % 2 == 0:
            engine.install_adapter(name, arrays)
        else:
            publish_adapter(
                {"type": "local", "path": t2_dir}, name, arrays, fingerprint
            )
            published.append(name)
    # wait for the hydrator's periodic rescan to discover the published
    # names (applying results here is loop-side: same event-loop thread
    # the engine's tier step uses)
    for _ in range(400):
        store.apply_results()
        if all(store.known(n) for n in names):
            break
        await asyncio.sleep(0.02)
    missing = [n for n in names if not store.known(n)]
    if missing:
        raise RuntimeError(f"T2 scan never discovered {missing}")

    async def _ask(tenant_i: int, name: str) -> float:
        result = await engine.generate(
            f"Tenant {tenant_i} asks via adapter {name}: status?",
            {"max-tokens": max_tokens, "temperature": 0, "adapter": name},
        )
        return float(result["ttft"])

    # warmup: compile the base path plus each device row's upload
    # program (.at[:, row].set is one XLA program per row index) —
    # first compiles must not land inside a measured TTFT
    await engine.generate(
        "warmup base path", {"max-tokens": 2, "temperature": 0}
    )
    installed = [n for i, n in enumerate(names) if i % 2 == 0]
    for name in (installed * t0_entries)[:t0_entries]:
        await _ask(-1, name)

    # a router beside the engine records the affinity semantics the
    # gateway would apply: first pick per adapter pins, repeats hit
    router = ReplicaRouter(fresh_s=3600.0)
    router.observe([
        {"replica": "bench-ai-0", "queued": 0, "occupancy": 0, "slots": 4},
        {"replica": "bench-ai-1", "queued": 0, "occupancy": 0, "slots": 4},
    ])

    JOURNEYS.clear()
    warm_ttfts: list[float] = []
    hydrate_ttfts: list[float] = []
    failures: list[str] = []
    submitted = 0
    t_start = time.monotonic()
    for _ in range(repeats):
        wave = []
        for i in range(tenants):
            name = names[i % adapters]
            router.pick(f"tenant-{i}", adapter=name)
            # resident => warm-path TTFT; not yet in T0/T1 => the TTFT
            # includes a hydration (classified at submit: concurrent
            # same-adapter requests ride the same fetch)
            resident = store.t1_has(name) or name in store.t0_resident()
            wave.append((resident, _ask(i, name)))
            submitted += 1
        results = await asyncio.gather(
            *(coro for _, coro in wave), return_exceptions=True
        )
        for (resident, _), result in zip(wave, results):
            if isinstance(result, BaseException):
                failures.append(f"{type(result).__name__}: {result}")
            elif resident:
                warm_ttfts.append(result)
            else:
                hydrate_ttfts.append(result)
    wall_s = time.monotonic() - t_start

    seg_samples: list[float] = []
    for jid in JOURNEYS.ids():
        for seg in journey_segments(JOURNEYS.events(jid)):
            if seg["segment"] == "adapter-hydrate":
                seg_samples.append(seg["ms"] / 1000.0)
    section = engine.stats()["adapters"]
    events = engine.flight.recent_events(0)
    event_counts: dict[str, int] = {}
    for e in events:
        if e["kind"].startswith("adapter-"):
            event_counts[e["kind"]] = event_counts.get(e["kind"], 0) + 1
    await engine.close()
    TpuServingEngine.reset_instances()

    def pct(values, q):
        v = _pct(values, q)
        return round(v, 4) if v is not None else None

    warm_ttfts.sort()
    hydrate_ttfts.sort()
    all_ttfts = sorted(warm_ttfts + hydrate_ttfts)
    t0 = section["t0"]
    ledger = section["ledger"]
    out: dict[str, Any] = {
        "tenants": tenants,
        "adapters": adapters,
        "t0_entries": t0_entries,
        "published_to_t2": len(published),
        "submitted": submitted,
        "completed": len(all_ttfts),
        "failures": failures,
        # zero silent loss: every request completed (a refused adapter
        # would surface here as a loud AdapterUnavailable)
        "zero_silent_loss": not failures and len(all_ttfts) == submitted,
        "multi_lora_ttft_p50_s": pct(all_ttfts, 0.50),
        "multi_lora_ttft_p99_s": pct(all_ttfts, 0.99),
        "multi_lora_warm_ttft_p50_s": pct(warm_ttfts, 0.50),
        "multi_lora_hydrate_ttft_p50_s": pct(hydrate_ttfts, 0.50),
        "multi_lora_hydrate_ttft_p99_s": pct(hydrate_ttfts, 0.99),
        "multi_lora_t0_hit_ratio": round(
            t0["hits"] / max(1, t0["hits"] + t0["loads"]), 4
        ),
        # eviction churn across every tier (T0 row churn + T1/T2)
        "multi_lora_evictions": t0["evictions"] + section["evictions"],
        "t0_evictions": t0["evictions"],
        "t0_loads": t0["loads"],
        "eviction_refusals": t0["eviction_refusals"],
        "hydrations": section["hydrations"],
        "hydrate_failures": section["hydrate_failures"],
        "fingerprint_refusals": section["fingerprint_refusals"],
        "ledger": ledger,
        "ledger_balanced": (
            ledger["t1_bytes"]
            + ledger["in_transit_bytes"]
            + ledger["t2_bytes"]
            == ledger["inserted_bytes"]
            + ledger["discovered_bytes"]
            - ledger["evicted_bytes"]
        ),
        "router": {
            "adapter_hits": router.stats()["adapter_hits"],
            "adapter_rerouted": router.stats()["adapter_rerouted"],
            "pinned_adapters": router.stats()["pinned_adapters"],
        },
        "flight_events": event_counts,
        "wall_s": round(wall_s, 3),
    }
    if seg_samples:
        seg_samples.sort()
        out["journey_segments"] = {
            "adapter-hydrate": {
                "p50_s": pct(seg_samples, 0.50),
                "p99_s": pct(seg_samples, 0.99),
                "n": len(seg_samples),
            }
        }
    return out


async def run_oom_storm_phase(
    *,
    serving: dict[str, Any] | None = None,
    requests: int = 24,
    max_tokens: int = 16,
    burst_after: int = 4,
    burst_count: int = 2,
) -> dict[str, Any]:
    """Survival phase (docs/RESILIENCE.md): flood one paged engine and
    inject a RESOURCE_EXHAUSTED burst at the pool-grow seam mid-phase
    (serving/faults.py), then record how the engine *adapted* — shrink
    and recover counts, shed rate, and the completed-vs-submitted
    ledger. The acceptance this phase instruments is zero silent loss:
    every submitted request either completes or is RateLimited with a
    retry hint; ``zero_silent_loss`` is the recorded verdict, and
    ``perf_diff`` declares the worse-directions so a regression that
    starts dropping work under pressure is flagged, not averaged away."""
    from langstream_tpu.serving.engine import ServingConfig, TpuServingEngine
    from langstream_tpu.serving.faults import FaultPlan
    from langstream_tpu.serving.qos import RateLimited

    serving = dict(serving or {})
    serving.setdefault("model", "tiny")
    serving.setdefault("slots", 4)
    serving.setdefault("max-seq-len", 256)
    serving.setdefault("decode-chunk", 4)
    serving.setdefault("model-dtype", "float32")
    serving.setdefault("kv-layout", "paged")
    serving.setdefault("kv-block-size", 16)
    serving.setdefault("shrink-recovery-s", 0.5)
    serving["faults"] = [
        {
            "site": "pool-grow",
            "shape": "oom",
            "after": burst_after,
            "count": burst_count,
        }
    ]
    config = ServingConfig.from_dict(serving)
    engine = TpuServingEngine(config)
    t_start = time.monotonic()
    results = await asyncio.gather(
        *(
            engine.generate(
                f"oom storm request {i} reporting in",
                {"max-tokens": max_tokens, "temperature": 0},
            )
            for i in range(requests)
        ),
        return_exceptions=True,
    )
    completed = sum(1 for r in results if isinstance(r, dict))
    shed = sum(1 for r in results if isinstance(r, RateLimited))
    other_failures = requests - completed - shed
    ttfts = sorted(r["ttft"] for r in results if isinstance(r, dict))
    # wait out the recovery probe: the phase records whether the budget
    # actually came back, not just that it shrank
    for _ in range(200):
        if not engine.stats()["survival"].get("withheld_blocks", 0):
            break
        await asyncio.sleep(0.05)
    survival = engine.stats()["survival"]
    events = engine.flight.recent_events(0)
    shrink_events = [e for e in events if e["kind"] == "pool-shrink"]
    await engine.close()
    TpuServingEngine.reset_instances()

    def pct(values, q):
        v = _pct(values, q)
        return round(v, 4) if v is not None else None

    return {
        "submitted": requests,
        "completed": completed,
        "shed": shed,
        "other_failures": other_failures,
        "oom_storm_completed_fraction": round(completed / requests, 4),
        "oom_storm_shed_rate": round(shed / requests, 4),
        # the acceptance ledger: every miss is a loud RateLimited shed
        "zero_silent_loss": (completed + shed) == requests,
        "oom_storm_shrinks": survival["shrinks"],
        "oom_storm_restores": survival["restores"],
        "shrink_preempted": survival["shrink_preempted"],
        "budget_recovered": not survival.get("withheld_blocks", 0),
        "faults_injected": sum(
            1 for e in events if e["kind"] == "fault-injected"
        ),
        "shrink_evidence": [
            {
                k: e.get(k)
                for k in (
                    "site", "withheld_blocks", "freed_blocks",
                    "preempted", "budget_blocks", "configured_blocks",
                )
            }
            for e in shrink_events
        ],
        "oom_storm_ttft_p50_s": pct(ttfts, 0.50),
        "oom_storm_ttft_p99_s": pct(ttfts, 0.99),
        "wall_s": round(time.monotonic() - t_start, 3),
    }


async def run_partition_storm_phase(
    *,
    serving: dict[str, Any] | None = None,
    requests: int = 16,
    max_tokens: int = 10,
    drop_after: int = 2,
    drop_count: int = 3,
) -> dict[str, Any]:
    """Cross-replica failure phase (docs/RESILIENCE.md "Distributed
    failure domain"): a prefill pool hands every request off through the
    :class:`~langstream_tpu.serving.handoff.HandoffChainer` to a
    two-replica decode pool where one replica is DEAD (every offer
    refuses the connection) and the network additionally drops a burst
    of offers mid-phase (``http-import`` fault site). Records what the
    resilience plane *did* about it — re-handoffs, breaker opens,
    local-decode fallbacks, deadline sheds — and the completed-vs-
    submitted ledger. The acceptance this phase instruments: zero silent
    loss and a breaker that keeps the dead replica out of the rotation;
    ``perf_diff`` declares the worse-directions so a regression that
    starts shedding (or falling back) under partition is flagged."""
    from langstream_tpu.gateway.router import ReplicaRouter
    from langstream_tpu.serving.engine import ServingConfig, TpuServingEngine
    from langstream_tpu.serving.handoff import (
        BreakerSpec,
        DeadlineExceeded,
        HandoffChainer,
        RetryPolicy,
    )
    from langstream_tpu.serving.qos import RateLimited

    serving = dict(serving or {})
    serving.setdefault("model", "tiny")
    serving.setdefault("slots", 4)
    serving.setdefault("max-seq-len", 256)
    serving.setdefault("decode-chunk", 4)
    serving.setdefault("model-dtype", "float32")
    serving.setdefault("kv-layout", "paged")
    serving.setdefault("kv-block-size", 16)
    serving.setdefault("prefix-cache", False)
    pre_cfg = ServingConfig.from_dict(
        {**serving, "pool-role": "prefill",
         # the mid-phase network partition: a burst of offers to the
         # LIVE replica drops too, so the chainer's backoff + re-route
         # discipline is exercised beyond the always-dead pod
         "faults": [{"site": "http-import", "shape": "drop",
                     "after": drop_after, "count": drop_count}]}
    )
    dec_cfg = ServingConfig.from_dict({**serving, "pool-role": "decode"})
    pre = TpuServingEngine(pre_cfg)
    dec = TpuServingEngine(dec_cfg)
    # open_s is SHORT so the live replica (whose offers the injected
    # drop burst also hits) rehabilitates through a half-open probe
    # mid-phase; the dead replica's probes keep failing, so it stays out
    # fresh_s: the phase observes once up front, and the first
    # generate pays the XLA compile — on a cold cache that alone
    # outlives the 15 s default, after which every pick would return
    # None and the whole phase would silently degenerate to local
    # fallbacks (the same guard the gateway phase's router carries)
    router = ReplicaRouter(
        fresh_s=3600.0, breaker=BreakerSpec(failures=2, open_s=0.25)
    )
    router.observe([
        {"replica": "pool-decode-0", "queued": 0, "occupancy": 0,
         "slots": serving["slots"], "pool": "decode"},
        {"replica": "pool-decode-1", "queued": 0, "occupancy": 0,
         "slots": serving["slots"], "pool": "decode"},
    ])

    async def transport(replica, payload, headers, timeout_s):
        if replica == "pool-decode-0":
            # the killed decode pod: connect refused, forever
            raise ConnectionError("connection refused (pod killed)")
        try:
            result = await dec.import_handoff(payload)
        except RateLimited as e:
            # the Transport contract (serving/handoff.py): sheds arrive
            # as HTTP answers, exactly as the pod handler maps them
            return 503, {"error": str(e), "retry_after_s": e.retry_after}, {}
        except DeadlineExceeded as e:
            return 504, {"error": str(e)}, {}
        return 200, result, {}

    chainer = HandoffChainer(
        pre, router=router, transport=transport,
        policy=RetryPolicy(attempts=4, backoff_s=0.01, backoff_cap_s=0.1),
    )
    t_start = time.monotonic()
    # bound in-flight handoffs to the pool's slot count: a local-decode
    # fallback needs a free slot, and an unbounded flood would convert
    # capacity waits into 503 sheds (imports shed rather than queue —
    # docs/DISAGG.md), which is not what this phase measures
    gate = asyncio.Semaphore(int(serving["slots"]))

    async def one(i: int) -> dict[str, Any]:
      async with gate:
        t0 = time.monotonic()
        ticket = await pre.generate(
            f"partition storm request {i} reporting in",
            {"max-tokens": max_tokens, "temperature": 0},
        )
        result = await chainer.chain(ticket)
        return {
            "wall_s": time.monotonic() - t0,
            "ttft_s": ticket.get("ttft", 0.0),
            "tokens": len(result.get("tokens") or ()),
        }

    results = await asyncio.gather(
        *(one(i) for i in range(requests)), return_exceptions=True
    )
    completed = [r for r in results if isinstance(r, dict)]
    shed = sum(
        1 for r in results if isinstance(r, (RateLimited, DeadlineExceeded))
    )
    other_failures = len(results) - len(completed) - shed
    ttfts = sorted(r["ttft_s"] for r in completed)
    walls = sorted(r["wall_s"] for r in completed)
    events = pre.flight.recent_events(0)
    survival = pre.stats()["survival"]
    rstats = router.stats()
    # the exclusion verdict reads the breaker STATE, not a post-phase
    # pick race: with open_s tuned short for mid-phase rehabilitation, a
    # pick can legitimately hand the dead replica a half-open PROBE —
    # what must never happen is its breaker closing (a probe succeeding)
    dead_state = rstats["breakers"].get("pool-decode-0", {}).get("state")
    await pre.close()
    await dec.close()
    TpuServingEngine.reset_instances()

    def pct(values, q):
        v = _pct(values, q)
        return round(v, 4) if v is not None else None

    return {
        "submitted": requests,
        "completed": len(completed),
        "shed": shed,
        "other_failures": other_failures,
        "partition_storm_completed_fraction": round(
            len(completed) / requests, 4
        ),
        "partition_storm_shed_rate": round(shed / requests, 4),
        "zero_silent_loss": (len(completed) + shed) == requests,
        # what the resilience plane did (the re-offer ledger)
        "partition_storm_rehandoffs": chainer.retries,
        "partition_storm_fallbacks": chainer.fallbacks,
        "partition_storm_breaker_opens": sum(
            b["opens"] for b in rstats["breakers"].values()
        ),
        "partition_storm_deadline_sheds": survival["deadline_sheds"],
        "breaker_open_replicas": rstats["breaker_open_replicas"],
        "dead_replica_excluded": dead_state in ("open", "half-open"),
        "faults_injected": sum(
            1 for e in events if e["kind"] == "fault-injected"
        ),
        "handoff_retry_events": sum(
            1 for e in events if e["kind"] == "handoff-retry"
        ),
        "partition_storm_ttft_p50_s": pct(ttfts, 0.50),
        "partition_storm_ttft_p99_s": pct(ttfts, 0.99),
        "partition_storm_wall_p99_s": pct(walls, 0.99),
        "wall_s": round(time.monotonic() - t_start, 3),
    }


if __name__ == "__main__":
    import os
    import sys
    from pathlib import Path

    # runnable from a checkout: `python tools/gateway_bench.py` (the same
    # bootstrap graftcheck/render_deploy use; bench.py imports us directly)
    sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

    if os.environ.get("JAX_PLATFORMS"):
        # the environment's TPU plugin overrides JAX_PLATFORMS at interpreter
        # start; the config knob is the override that actually sticks
        import jax

        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    out = asyncio.run(
        run_gateway_bench(
            {
                "model": "tiny",
                "slots": 4,
                "max-seq-len": 128,
                "decode-chunk": 8,
            },
            prompt="ping",
            max_tokens=8,
            requests=12,
            warmup=2,
            arrival_rate_hz=8.0,
        )
    )
    print(json.dumps(out))
