"""Generate golden model-math fixtures from HuggingFace's Llama reference.

Run ONCE (the outputs are checked in under ``tests/fixtures/llama_tiny_golden``):

    python tools/gen_golden_fixtures.py

Produces, for the tiny config (matching ``LlamaConfig.tiny``):
- ``pytorch_model.bin`` — HF-format state dict (the checkpoint loader's
  input format), deterministic random init;
- ``golden.npz`` — prompt token ids, HF all-position logits (fp32, eager
  attention), and HF greedy continuations.

The test suite loads the weights through
``langstream_tpu.models.checkpoints.load_llama_checkpoint`` and asserts the
JAX forward/prefill/decode reproduce these outputs — pinning RoPE layout,
GQA grouping, normalization placement, and the HF tensor-name mapping to an
independent implementation (a wrong-RoPE mutation fails this, where the
repo's internal equivalence tests would pass symmetrically).
"""

from __future__ import annotations

from pathlib import Path

import numpy as np
import torch

import sys

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))
OUT = REPO / "tests" / "fixtures" / "llama_tiny_golden"

from langstream_tpu.models.llama import LlamaConfig as _JaxConfig  # noqa: E402

_TINY = _JaxConfig.tiny(max_seq_len=128)  # the config the tests pin against
VOCAB = _TINY.vocab_size
HIDDEN = _TINY.hidden
LAYERS = _TINY.layers
HEADS = _TINY.heads
KV_HEADS = _TINY.kv_heads
INTERMEDIATE = _TINY.intermediate
ROPE_THETA = _TINY.rope_theta
NORM_EPS = _TINY.norm_eps
MAX_SEQ = _TINY.max_seq_len
assert _TINY.head_dim == HIDDEN // HEADS, "HF derives head_dim = hidden/heads"


def main() -> None:
    from transformers import LlamaConfig, LlamaForCausalLM

    config = LlamaConfig(
        vocab_size=VOCAB,
        hidden_size=HIDDEN,
        num_hidden_layers=LAYERS,
        num_attention_heads=HEADS,
        num_key_value_heads=KV_HEADS,
        intermediate_size=INTERMEDIATE,
        rope_theta=ROPE_THETA,
        rms_norm_eps=NORM_EPS,
        max_position_embeddings=MAX_SEQ,
        tie_word_embeddings=False,
        attention_bias=False,
        mlp_bias=False,
        attn_implementation="eager",
    )
    torch.manual_seed(1234)
    model = LlamaForCausalLM(config)
    model.eval()

    rng = np.random.default_rng(42)
    # two prompts of different lengths (right-padding handled caller-side)
    prompts = [
        rng.integers(0, VOCAB, size=17).tolist(),
        rng.integers(0, VOCAB, size=9).tolist(),
    ]

    OUT.mkdir(parents=True, exist_ok=True)
    torch.save(model.state_dict(), OUT / "pytorch_model.bin")

    arrays: dict[str, np.ndarray] = {}
    with torch.no_grad():
        for p, tokens in enumerate(prompts):
            ids = torch.tensor([tokens], dtype=torch.long)
            logits = model(ids).logits[0].float().numpy()  # (S, V)
            arrays[f"prompt_{p}"] = np.asarray(tokens, dtype=np.int32)
            arrays[f"logits_{p}"] = logits
            generated = model.generate(
                ids, max_new_tokens=8, do_sample=False,
                pad_token_id=0,
                # explicit mask: without it HF infers (ids != pad_token_id),
                # silently masking any real token id 0 in the prompt
                attention_mask=torch.ones_like(ids),
            )[0, len(tokens):].numpy().astype(np.int32)
            arrays[f"greedy_{p}"] = generated
    np.savez(OUT / "golden.npz", **arrays)
    print(f"wrote {OUT}/pytorch_model.bin and golden.npz "
          f"({len(prompts)} prompts)")


if __name__ == "__main__":
    main()
