"""Generate golden MoE fixtures from HuggingFace's Mixtral reference.

Run ONCE (outputs checked in under ``tests/fixtures/moe_tiny_golden``):

    python tools/gen_moe_golden_fixtures.py

Same role as ``gen_golden_fixtures.py`` for the dense family: the MoE
forward (router softmax, top-2 renormalized combine, expert SwiGLU,
shared attention) and the Mixtral checkpoint-name mapping get pinned to
an independent implementation. HF routes every token dropless; the test
raises ``capacity_factor`` so the GShard capacity path is in its
drop-free regime where the two formulations agree exactly.
"""

from __future__ import annotations

import sys
from pathlib import Path

import numpy as np
import torch

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))
OUT = REPO / "tests" / "fixtures" / "moe_tiny_golden"

from langstream_tpu.models.moe import MoEConfig as _JaxConfig  # noqa: E402

_TINY = _JaxConfig.tiny(max_seq_len=128)


def main() -> None:
    from transformers import MixtralConfig, MixtralForCausalLM

    config = MixtralConfig(
        vocab_size=_TINY.vocab_size,
        hidden_size=_TINY.hidden,
        num_hidden_layers=_TINY.layers,
        num_attention_heads=_TINY.heads,
        num_key_value_heads=_TINY.kv_heads,
        intermediate_size=_TINY.moe_intermediate,
        num_local_experts=_TINY.experts,
        num_experts_per_tok=_TINY.experts_per_token,
        rope_theta=_TINY.rope_theta,
        rms_norm_eps=_TINY.norm_eps,
        max_position_embeddings=_TINY.max_seq_len,
        tie_word_embeddings=False,
        attention_bias=False,
        sliding_window=None,
        attn_implementation="eager",
        router_jitter_noise=0.0,
    )
    torch.manual_seed(4321)
    model = MixtralForCausalLM(config)
    model.eval()

    rng = np.random.default_rng(7)
    prompts = [
        rng.integers(0, _TINY.vocab_size, size=13).tolist(),
        rng.integers(0, _TINY.vocab_size, size=7).tolist(),
    ]

    OUT.mkdir(parents=True, exist_ok=True)
    torch.save(model.state_dict(), OUT / "pytorch_model.bin")

    fixtures: dict[str, np.ndarray] = {}
    with torch.no_grad():
        for i, prompt in enumerate(prompts):
            ids = torch.tensor([prompt], dtype=torch.long)
            logits = model(ids).logits[0].float().numpy()
            fixtures[f"prompt_{i}"] = np.asarray(prompt, dtype=np.int32)
            fixtures[f"logits_{i}"] = logits
            greedy = model.generate(
                ids, max_new_tokens=6, do_sample=False,
                pad_token_id=0,
            )[0, len(prompt):].numpy()
            fixtures[f"greedy_{i}"] = greedy.astype(np.int32)
    np.savez(OUT / "golden.npz", **fixtures)
    print("wrote", OUT)


if __name__ == "__main__":
    main()
