#!/usr/bin/env python
"""graftcheck launcher — static analysis for the langstream-tpu tree.

Thin wrapper so the analyzer runs from a checkout without installing the
package: ``python tools/graftcheck.py [--changed|paths...]``. All logic
lives in ``langstream_tpu/analysis`` (see ``docs/ANALYSIS.md``).
"""

from __future__ import annotations

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

from langstream_tpu.analysis.__main__ import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main())
