#!/usr/bin/env python3
"""journey: render stitched request journeys, attribute the TTFT
critical path, and flag lifecycle anomalies.

The request journey plane (serving/journey.py, docs/OBSERVABILITY.md)
records one append-only event list per request across every pod it
touched; the control plane stitches the partials under
``/api/applications/{t}/{n}/journey/{id}``. This tool is the operator
end of that plane:

- **waterfall** (default): one stitched journey rendered as a span
  waterfall — every lifecycle edge with its offset, and each inter-event
  segment (queue / prefill / export / transfer / decode-admission /
  first-step / decode …) as a scaled bar, so "where did this request's
  7.8 s go" reads off one screen;
- **critical path**: per journey, the segment that dominated its TTFT
  (submit → first visible token), and over a SET of journeys the
  p50/p99 per segment plus a histogram of which segment dominated —
  the aggregate that tells you whether to attack the queue, the
  prefill, or the handoff;
- **anomalies**: transfer time exceeding prefill time (disaggregation
  costing more than it saves), a re-prefill after preemption (the
  resume re-pays the prompt), and more than ``--max-bounces`` replica
  bounces (routing thrash).

    python tools/journey.py stitched.json                  # waterfall
    python tools/journey.py --url http://cp:8090/api/applications/t/app/journey/<id>
    python tools/journey.py --aggregate dump1.json dump2.json ...
    python tools/journey.py --trace <id> dump.json         # exemplar -> journey

``--trace <id>`` filters the inputs to one journey by id — the
resolution step for a ``/metrics`` histogram exemplar: the exemplar's
``trace_id`` IS the journey id, so a p99 bucket observation resolves to
the full lifecycle of the request that landed it (exit 2 when absent).

Accepted inputs (auto-detected per file): a stitched journey payload
(the control-plane route's shape), a list of stitched journeys, a raw
event list or list of per-pod partial event lists (stitched locally —
the same ordering-and-classify rules as serving/journey.py, duplicated
here so the tool stays dependency-free; ``tests/test_journey.py`` pins
the two tables equal), or a ``/journey`` pod payload.

Zero dependencies (stdlib only), like ``engine_top``.
"""

from __future__ import annotations

import argparse
import json
import sys
import urllib.request

#: (previous kind, next kind) → segment name — MUST match
#: serving/journey.py's EDGE_SEGMENTS (pinned by tests/test_journey.py)
EDGE_SEGMENTS: dict[tuple[str, str], str] = {
    ("gateway-produce", "submit"): "ingest",
    ("bounce", "submit"): "ingest",
    ("gateway-produce", "bounce"): "ingest",
    ("bounce", "bounce"): "ingest",
    ("submit", "admit"): "queue",
    ("submit", "shed"): "queue",
    # tiered prefix store (docs/PREFIX.md): an admission stashed while
    # the hydrator pulls its prompt's T2 blobs into T1 — the interval
    # the warm-start either pays instead of prefill or writes off at
    # the hydrate timeout
    ("submit", "hydrate-begin"): "queue",
    ("hydrate-begin", "hydrate-done"): "prefix-hydrate",
    ("hydrate-done", "admit"): "queue",
    # tiered adapter store (docs/ADAPTERS.md): an admission stashed
    # while the hydrator pulls the request's LoRA factors T2→T1 — the
    # cold-start interval an adapter pays once per replica, or writes
    # off at the hydrate timeout (a cold refusal: no recompute fallback)
    ("submit", "adapter-hydrate"): "queue",
    ("hydrate-done", "adapter-hydrate"): "queue",
    ("adapter-hydrate", "adapter-hydrate-done"): "adapter-hydrate",
    ("adapter-hydrate-done", "admit"): "queue",
    ("adapter-hydrate", "cancelled"): "adapter-hydrate",
    ("admit", "first-token"): "prefill",
    ("first-token", "export"): "export",
    ("export", "export-taken"): "handoff-wait",
    ("export-taken", "import-received"): "transfer",
    ("export", "import-received"): "transfer",
    ("import-received", "import"): "decode-admission",
    ("import", "first-step"): "first-step",
    ("first-step", "finish"): "decode",
    ("first-token", "finish"): "decode",
    ("preempt", "resume"): "preempted",
    ("resume", "admit"): "requeue",
    ("first-token", "preempt"): "decode",
    ("first-step", "preempt"): "decode",
    ("admit", "finish"): "decode",
    ("first-token", "first-emit"): "decode",
    ("first-step", "first-emit"): "decode",
    ("first-emit", "last-emit"): "stream",
    ("last-emit", "finish"): "decode",
    ("first-emit", "finish"): "decode",
    ("first-emit", "cancelled"): "stream",
    ("last-emit", "cancelled"): "decode",
}

#: segments that are part of TTFT (everything before the first token
#: the CLIENT can see: the decode pool's first step for a handoff, the
#: first-token edge otherwise)
TTFT_SEGMENTS = (
    "ingest", "queue", "prefix-hydrate", "adapter-hydrate", "prefill",
    "export", "handoff-wait", "transfer",
    "decode-admission", "first-step", "preempted", "requeue",
)

#: the handoff cost a disaggregated fleet pays on top of a co-located
#: run — compared against prefill for the transfer-dominated flag
HANDOFF_SEGMENTS = ("export", "handoff-wait", "transfer", "decode-admission")


def classify_edge(prev_kind: str, next_kind: str) -> str:
    return EDGE_SEGMENTS.get(
        (prev_kind, next_kind), f"{prev_kind}->{next_kind}"
    )


def stitch_events(journey_id: str, partials: list) -> dict:
    """Local stitch over raw partial event lists (same semantics as
    serving/journey.py stitch: stable sort on the wall anchor, tiling
    segment decomposition)."""
    tagged = []
    for pi, part in enumerate(partials):
        for idx, event in enumerate(part or []):
            if isinstance(event, dict):
                tagged.append(
                    (float(event.get("t_ms") or 0.0), pi, idx, event)
                )
    tagged.sort(key=lambda t: (t[0], t[1], t[2]))
    events = [t[3] for t in tagged]
    segments = []
    for prev, nxt in zip(events, events[1:]):
        segments.append(
            {
                "segment": classify_edge(
                    str(prev.get("kind")), str(nxt.get("kind"))
                ),
                "from": prev.get("kind"),
                "to": nxt.get("kind"),
                "t_ms": prev.get("t_ms"),
                "ms": round(
                    float(nxt.get("t_ms") or 0.0)
                    - float(prev.get("t_ms") or 0.0),
                    3,
                ),
            }
        )
    by_segment: dict[str, float] = {}
    for seg in segments:
        by_segment[seg["segment"]] = round(
            by_segment.get(seg["segment"], 0.0) + seg["ms"], 3
        )
    total = (
        round(
            float(events[-1].get("t_ms") or 0.0)
            - float(events[0].get("t_ms") or 0.0),
            3,
        )
        if events
        else 0.0
    )
    return {
        "journey": journey_id,
        "events": events,
        "segments": segments,
        "by_segment_ms": by_segment,
        "total_ms": total,
    }


def _is_event(obj) -> bool:
    return isinstance(obj, dict) and "kind" in obj and "t_ms" in obj


def load_journeys(payload, label: str = "journey") -> list[dict]:
    """Normalize any accepted input shape into stitched journey dicts."""
    if isinstance(payload, dict):
        if isinstance(payload.get("segments"), list) and isinstance(
            payload.get("events"), list
        ):
            return [payload]                      # already stitched
        if isinstance(payload.get("journeys"), list):
            out = []
            for i, sub in enumerate(payload["journeys"]):
                out.extend(load_journeys(sub, f"{label}[{i}]"))
            return out
        return []
    if isinstance(payload, list):
        if all(_is_event(e) for e in payload) and payload:
            return [stitch_events(label, [payload])]   # raw event list
        if payload and all(
            isinstance(p, list) and all(_is_event(e) for e in p)
            for p in payload
        ):
            return [stitch_events(label, payload)]     # per-pod partials
        out = []
        for i, sub in enumerate(payload):
            out.extend(load_journeys(sub, f"{label}[{i}]"))
        return out
    return []


# ---------------------------------------------------------------------------
# analysis
# ---------------------------------------------------------------------------


def by_segment(journey: dict) -> dict[str, float]:
    if isinstance(journey.get("by_segment_ms"), dict):
        return dict(journey["by_segment_ms"])
    totals: dict[str, float] = {}
    for seg in journey.get("segments") or []:
        totals[seg["segment"]] = totals.get(seg["segment"], 0.0) + (
            seg.get("ms") or 0.0
        )
    return totals


def _ttft_cutoff(events: list) -> int | None:
    """Index of the first CLIENT-visible token edge: the decode pool's
    ``first-step`` when the journey handed off, ``first-token``
    otherwise. None when the journey never produced one."""
    kinds = [str(e.get("kind")) for e in events]
    if "first-step" in kinds:
        return kinds.index("first-step")
    if "first-token" in kinds:
        return kinds.index("first-token")
    return None


def ttft_critical_path(journey: dict) -> tuple[str, float] | None:
    """(dominant segment, its ms) over the journey's TTFT — the
    timeline UP TO the first client-visible token. Segments after it
    (a mid-decode preemption, the decode itself) never enter, so a 5 s
    decode-phase preempt can't masquerade as a TTFT problem. Falls back
    to the name-based filter when the payload carries no events."""
    events = journey.get("events") or []
    cutoff = _ttft_cutoff(events)
    if cutoff is not None:
        totals: dict[str, float] = {}
        for prev, nxt in zip(events[:cutoff], events[1 : cutoff + 1]):
            name = classify_edge(str(prev.get("kind")), str(nxt.get("kind")))
            totals[name] = totals.get(name, 0.0) + (
                float(nxt.get("t_ms") or 0.0) - float(prev.get("t_ms") or 0.0)
            )
        ttft = {k: v for k, v in totals.items() if v > 0}
    else:
        ttft = {
            k: v
            for k, v in by_segment(journey).items()
            if k in TTFT_SEGMENTS and v > 0
        }
    if not ttft:
        return None
    name = max(ttft, key=lambda k: ttft[k])
    return name, round(ttft[name], 3)


def journey_flags(journey: dict, max_bounces: int = 3) -> list[str]:
    """Per-journey anomaly flags."""
    flags = list(journey.get("anomalies") or [])
    totals = by_segment(journey)
    handoff = sum(totals.get(s, 0.0) for s in HANDOFF_SEGMENTS)
    prefill = totals.get("prefill", 0.0)
    if handoff and prefill and handoff > prefill:
        flags.append(
            f"transfer-dominated TTFT: handoff cost {handoff:.1f}ms "
            f"(export+wait+transfer+admission) exceeds prefill "
            f"{prefill:.1f}ms — disaggregation is costing more than it "
            f"saves on this request"
        )
    kinds = [str(e.get("kind")) for e in journey.get("events") or []]
    if "preempt" in kinds and kinds.count("admit") > 1:
        flags.append(
            "re-prefill after preempt: the resume re-paid the prompt's "
            "prefill — expected under KV pressure/drain, but a hot loop "
            "of these means the pool is undersized"
        )
    bounces = kinds.count("bounce")
    if bounces > max_bounces:
        flags.append(
            f"{bounces} replica bounces (> {max_bounces}): the routing "
            f"target keeps moving — check fleet churn or stale router "
            f"snapshots"
        )
    return flags


def _pct(sorted_values: list[float], q: float) -> float:
    return sorted_values[
        min(len(sorted_values) - 1, int(q * len(sorted_values)))
    ]


def aggregate(journeys: list[dict]) -> dict:
    """p50/p99 per segment over a set of journeys + the critical-path
    histogram (which segment dominated each journey's TTFT)."""
    samples: dict[str, list[float]] = {}
    dominated: dict[str, int] = {}
    for journey in journeys:
        for name, ms in by_segment(journey).items():
            samples.setdefault(name, []).append(ms)
        critical = ttft_critical_path(journey)
        if critical is not None:
            dominated[critical[0]] = dominated.get(critical[0], 0) + 1
    segments = {}
    for name, values in samples.items():
        values = sorted(values)
        segments[name] = {
            "n": len(values),
            "p50_ms": round(_pct(values, 0.50), 3),
            "p99_ms": round(_pct(values, 0.99), 3),
        }
    return {
        "journeys": len(journeys),
        "segments": segments,
        "ttft_critical_path": dict(
            sorted(dominated.items(), key=lambda kv: -kv[1])
        ),
    }


# ---------------------------------------------------------------------------
# rendering
# ---------------------------------------------------------------------------


def render_waterfall(journey: dict, width: int = 40) -> str:
    events = journey.get("events") or []
    segments = journey.get("segments") or []
    total = float(journey.get("total_ms") or 0.0) or 1.0
    lines = [
        f"== journey {journey.get('journey', '?')} ==  "
        f"{len(events)} events over {journey.get('total_ms', 0.0):.1f}ms"
        + ("" if journey.get("complete", True) else "  [INCOMPLETE]")
    ]
    if events:
        t0 = float(events[0].get("t_ms") or 0.0)
        for event in events:
            offset = float(event.get("t_ms") or 0.0) - t0
            pod = f" @{event['pod']}" if event.get("pod") else ""
            detail = {
                k: v
                for k, v in event.items()
                if k not in ("kind", "t_ms", "m_s", "seq", "pod")
                and v is not None
            }
            lines.append(
                f"  {offset:9.1f}ms  {str(event.get('kind')):16s}{pod}"
                + (f"  {detail}" if detail else "")
            )
    if segments:
        lines.append("  --")
        for seg in segments:
            frac = max(0.0, (seg.get("ms") or 0.0) / total)
            bar = "█" * max(
                1 if (seg.get("ms") or 0.0) > 0 else 0,
                int(round(frac * width)),
            )
            lines.append(
                f"  {seg['segment']:18s} {seg.get('ms', 0.0):9.1f}ms  {bar}"
            )
    critical = ttft_critical_path(journey)
    if critical is not None:
        lines.append(
            f"  critical path: {critical[0]} ({critical[1]:.1f}ms of the "
            f"TTFT)"
        )
    for flag in journey_flags(journey):
        lines.append(f"  !! {flag}")
    return "\n".join(lines)


def render_aggregate(agg: dict) -> str:
    lines = [f"== {agg['journeys']} journeys =="]
    lines.append("  segment             n      p50        p99")
    for name in sorted(
        agg["segments"], key=lambda n: -agg["segments"][n]["p50_ms"]
    ):
        entry = agg["segments"][name]
        lines.append(
            f"  {name:18s} {entry['n']:4d} {entry['p50_ms']:8.1f}ms "
            f"{entry['p99_ms']:9.1f}ms"
        )
    if agg["ttft_critical_path"]:
        dominated = "  ".join(
            f"{name}:{count}"
            for name, count in agg["ttft_critical_path"].items()
        )
        lines.append(f"  TTFT dominated by   {dominated}")
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# entry point
# ---------------------------------------------------------------------------


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        description="render stitched request journeys; attribute the "
        "TTFT critical path"
    )
    parser.add_argument(
        "files", nargs="*",
        help="stitched journey dumps (control-plane /journey/{id} "
        "payloads, raw event lists, or lists of either)",
    )
    parser.add_argument(
        "--url", help="fetch one journey from a control-plane/pod URL"
    )
    parser.add_argument(
        "--aggregate", action="store_true",
        help="p50/p99 per segment + critical-path histogram over every "
        "journey in the inputs (instead of one waterfall each)",
    )
    parser.add_argument(
        "--max-bounces", type=int, default=3,
        help="replica bounces beyond this are flagged (default 3)",
    )
    parser.add_argument(
        "--trace",
        metavar="ID",
        help="render only the journey with this id — the resolution step "
        "for a /metrics exemplar's trace_id (exit 2 when the inputs "
        "hold no such journey)",
    )
    parser.add_argument(
        "--json", action="store_true", help="emit the analysis as JSON"
    )
    args = parser.parse_args(argv)
    if not args.files and not args.url:
        parser.error("need journey dump files or --url")

    journeys: list[dict] = []
    try:
        if args.url:
            with urllib.request.urlopen(args.url, timeout=10) as resp:
                journeys.extend(load_journeys(json.loads(resp.read())))
        for path in args.files:
            with open(path) as f:
                journeys.extend(load_journeys(json.load(f), label=path))
    except (OSError, ValueError) as e:
        print(f"journey load failed: {e}", file=sys.stderr)
        return 2
    if not journeys:
        print(
            "no journeys found (expected a stitched /journey payload, a "
            "raw event list, or a list of either)",
            file=sys.stderr,
        )
        return 2
    if args.trace:
        journeys = [
            j for j in journeys if str(j.get("journey")) == args.trace
        ]
        if not journeys:
            print(
                f"no journey {args.trace!r} in the inputs — if the id came "
                f"from a /metrics exemplar, fetch the stitched payload "
                f"from the control plane's /journey/{args.trace} route "
                f"first",
                file=sys.stderr,
            )
            return 2

    if args.aggregate:
        agg = aggregate(journeys)
        print(json.dumps(agg, indent=2) if args.json else render_aggregate(agg))
        return 0
    if args.json:
        print(
            json.dumps(
                [
                    {
                        "journey": j.get("journey"),
                        "by_segment_ms": by_segment(j),
                        "critical_path": ttft_critical_path(j),
                        "flags": journey_flags(j, args.max_bounces),
                    }
                    for j in journeys
                ],
                indent=2,
            )
        )
        return 0
    for journey in journeys:
        print(render_waterfall(journey))
        print()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
