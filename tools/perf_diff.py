#!/usr/bin/env python3
"""perf diff: the perf-regression sentry over bench/flight rollups.

The bench trajectory (``BENCH_r*.json``) and saved ``/flight`` dumps
are a monitored series, not JSON archaeology: this tool aligns two or
more rounds and flags the metrics that regressed beyond a noise band —
step time, overlap ratio, HBM utilization, speculative uplift, gateway
TTFT, throughput — each with its direction of "worse" declared, so a
30% step-time regression is flagged as exactly that and identical
rollups stay quiet.

    python tools/perf_diff.py BENCH_r05.json BENCH_r06.json
    python tools/perf_diff.py BENCH_r0*.json --threshold 0.2
    python tools/perf_diff.py old_flight.json new_flight.json --json

Accepted inputs (auto-detected per file):

- bench records (``bench.py`` output: ``{"metric", "value", "detail"}``,
  schema-stamped from BENCH_r06 on — see BENCH_NOTES.md);
- saved ``/flight`` payloads (a list of engine entries with
  ``summary.totals``/``summary.window``);
- bare flight rollups (``bench_rollup`` dicts).

Alignment: metrics are extracted into one flat namespace per file; only
metrics present in BOTH sides of a pair are compared (a phase that was
skipped in one round is reported as coverage drift, not a regression).
The bench record's ``schema`` version and program-variant census ride
along: a census change between rounds is annotated so a step-time shift
can be read against "the engine compiles different programs now".

``engine_top --analyze A.json B.json`` runs the same diff. Exit code:
0 quiet, 1 when any regression is flagged, 2 on usage errors.
Zero dependencies (stdlib only).

``--gate`` additionally judges the metrics in ``GATE_THRESHOLDS``
against their own per-metric limit — product SLOs, not noise bands, so
a gated regression fails the run (exit 1) even inside the default
threshold. The first gated direction is the streaming TBT p99
(``gateway_stream_tbt_p99_s``): ROADMAP item 5's chip-measured TBT
gate, holding future bench rounds to the product-latency guarantee.
"""

from __future__ import annotations

import argparse
import json
import sys

#: metric name → direction in which it gets WORSE ("up" = a higher
#: value is a regression). Every comparison key must be declared here —
#: an undeclared metric is ignored rather than guessed.
METRICS: dict[str, str] = {
    "tok_s": "down",
    "step_ms_p50": "up",
    "mean_step_ms": "up",
    "host_exposed_ms_p50": "up",
    "host_overhead_ms_p50": "up",
    "overlap_ratio": "down",
    "hbm_utilization": "down",
    "speculative_uplift": "down",
    "speculative_accepted_per_step": "down",
    # device-resident decode tail (fused sampler): the engine's one-
    # packed-fetch-per-chunk invariant on the record — any drift above
    # 1.0 means the tail re-crossed the host boundary
    "decode_host_fetches_per_chunk": "up",
    # engine-measured rolling uplift (spec window vs plain calibration
    # chunks) and per-step fused fetch ratio from the speculation
    # section of schema-2 records
    "speculative_measured_uplift": "down",
    "speculative_fetches_per_step": "up",
    "gateway_ttft_p50_s": "up",
    "prefix_cache_speedup": "down",
    "recompile_count": "up",
    # tiered prefix store (docs/PREFIX.md, gateway_bench warm-prefix
    # phase): warm/hydrated TTFT grows = regression, hydrate-vs-
    # recompute speedup shrinks = regression
    "prefix_warm_ttft_p50_s": "up",
    "prefix_warm_ttft_p99_s": "up",
    "prefix_hydrate_ttft_s": "up",
    "prefix_hydrate_speedup": "down",
    "journey_prefix_hydrate_p50_s": "up",
    "journey_prefix_hydrate_p99_s": "up",
    # per-request journey segments (serving/journey.py, recorded by
    # gateway_bench as `journey_segments`): every TTFT component is
    # worse when it grows — the instrument for the split-pool bench
    # round (ROADMAP item 3)
    "journey_ingest_p50_s": "up",
    "journey_ingest_p99_s": "up",
    "journey_queue_p50_s": "up",
    "journey_queue_p99_s": "up",
    "journey_prefill_p50_s": "up",
    "journey_prefill_p99_s": "up",
    "journey_transfer_p50_s": "up",
    "journey_transfer_p99_s": "up",
    "journey_decode_admission_p50_s": "up",
    "journey_decode_admission_p99_s": "up",
    "journey_first_step_p50_s": "up",
    "journey_first_step_p99_s": "up",
    # device-survival storm (docs/RESILIENCE.md, gateway_bench
    # run_oom_storm_phase): more sheds / fewer completions under the
    # same injected burst = the adaptation regressed; more shrinks =
    # the engine needed more budget cuts to survive the same pressure;
    # a slower p99 = the storm leaked into latency it used to absorb
    "oom_storm_shed_rate": "up",
    "oom_storm_completed_fraction": "down",
    "oom_storm_shrinks": "up",
    "oom_storm_ttft_p50_s": "up",
    "oom_storm_ttft_p99_s": "up",
    # cross-replica failure storm (gateway_bench run_partition_storm_
    # phase): more sheds / fewer completions / more local-decode
    # fallbacks / slower TTFT under a dead decode replica is the
    # resilience plane regressing
    "partition_storm_shed_rate": "up",
    "partition_storm_completed_fraction": "down",
    "partition_storm_fallbacks": "up",
    "partition_storm_ttft_p99_s": "up",
    # streaming-delivery phase (docs/OBSERVABILITY.md Streaming,
    # gateway_bench run_stream_phase): client-observed time-between-
    # frames growing, streams stalling, the first frame arriving later,
    # or disconnect-cancelled slots reclaiming less than 1:1 is the
    # streaming plane regressing
    "gateway_stream_tbt_p50_s": "up",
    "gateway_stream_tbt_p99_s": "up",
    "gateway_stream_stalls": "up",
    "gateway_stream_ttfb_s": "up",
    "gateway_stream_cancel_reclaim_fraction": "down",
    # multi-LoRA adapter phase (docs/ADAPTERS.md, gateway_bench
    # run_multi_lora_phase): TTFT growing under the same adapter mix,
    # the T0 hit ratio shrinking, eviction churn rising, or hydrations
    # slowing is the adapter plane regressing
    "multi_lora_ttft_p99_s": "up",
    "multi_lora_t0_hit_ratio": "down",
    "multi_lora_evictions": "up",
    "multi_lora_hydrate_ttft_p99_s": "up",
    "journey_adapter_hydrate_p50_s": "up",
    "journey_adapter_hydrate_p99_s": "up",
    # analyzer self-stats (bench.py _analyzer_stats): the tier-1 gate
    # pays the analyzer's wall time every run, and a growing suppression
    # count is escape-hatch creep — both get worse upward
    "analyzer_wall_s": "up",
    "analyzer_suppressions": "up",
}

#: default noise band: relative change below this is never flagged
DEFAULT_THRESHOLD = 0.15

#: SLO gate thresholds (``--gate``): metric → the maximum tolerated
#: relative regression in its declared worse direction. These are
#: product guarantees, not noise bands — they may sit BELOW the default
#: threshold, and crossing one fails the gate (non-zero exit) even when
#: the ordinary diff would have stayed quiet. First gated direction:
#: the streaming time-between-tokens p99 (ROADMAP item 5's
#: "chip-measured TBT gate" — bench rounds are held to the SLO the
#: decode-chunk tuning promised, not to vibes).
GATE_THRESHOLDS: dict[str, float] = {
    "gateway_stream_tbt_p99_s": 0.10,
}


def gate_violations(base_m: dict, new_m: dict) -> list[dict]:
    """Gated metrics that regressed past their own threshold between two
    extracted metric dicts. Same direction/relative-change arithmetic as
    :func:`diff_metrics`, but judged against :data:`GATE_THRESHOLDS`
    per metric instead of the shared noise band."""
    violations: list[dict] = []
    for metric, limit in GATE_THRESHOLDS.items():
        worse = METRICS.get(metric)
        if worse is None:
            continue
        b, n = base_m.get(metric), new_m.get(metric)
        if not isinstance(b, (int, float)) or not isinstance(n, (int, float)):
            continue
        if b == 0:
            continue
        change = (n - b) / abs(b)
        regressed = change > 0 if worse == "up" else change < 0
        if regressed and abs(change) > limit:
            violations.append(
                {
                    "metric": metric,
                    "base": b,
                    "new": n,
                    "change": round(change, 4),
                    "limit": limit,
                }
            )
    return violations


def _first(d: dict, *keys, default=None):
    for key in keys:
        if isinstance(d, dict) and d.get(key) is not None:
            return d[key]
    return default


def _journey_metrics(section, metrics: dict) -> None:
    """Flatten a ``journey_segments`` section ({segment: {p50_s, p99_s}})
    into the declared ``journey_<segment>_<q>`` metric names. Segments
    without a declared direction are ignored, never guessed."""
    if not isinstance(section, dict):
        return
    for segment, values in section.items():
        if not isinstance(values, dict):
            continue
        key = "journey_" + str(segment).replace("-", "_")
        for quantile in ("p50_s", "p99_s"):
            name = f"{key}_{quantile}"
            if name in METRICS and values.get(quantile) is not None:
                metrics.setdefault(name, values[quantile])


def _walk_flight_rollups(obj, found: list[dict]) -> None:
    """Every flight-rollup-shaped dict in the payload (bench ``flight``
    keys or ``summary`` entries of a /flight dump)."""
    if isinstance(obj, dict):
        totals = (obj.get("summary") or {}).get("totals") or obj.get("totals")
        if isinstance(totals, dict) and "device_ms" in totals:
            found.append(obj)
            return
        for value in obj.values():
            _walk_flight_rollups(value, found)
    elif isinstance(obj, list):
        for value in obj:
            _walk_flight_rollups(value, found)


def extract_metrics(payload) -> dict:
    """Flatten one file's payload into ``{metric: value}`` plus the
    alignment context (``schema``, program census)."""
    out: dict = {"metrics": {}, "schema": None, "programs": {}}
    metrics = out["metrics"]

    if isinstance(payload, dict) and "detail" in payload:
        # bench record
        out["schema"] = payload.get("schema")
        if isinstance(payload.get("value"), (int, float)):
            metrics["tok_s"] = payload["value"]
        detail = payload.get("detail") or {}
        # headline leg: the kv-layout entry carrying the roofline
        for leg in detail.values():
            if not isinstance(leg, dict):
                continue
            roofline = leg.get("roofline")
            if isinstance(roofline, dict):
                if roofline.get("hbm_utilization") is not None:
                    metrics.setdefault(
                        "hbm_utilization", roofline["hbm_utilization"]
                    )
                if leg.get("mean_step_ms") is not None:
                    metrics.setdefault("mean_step_ms", leg["mean_step_ms"])
                if leg.get("overlap_ratio") is not None:
                    metrics.setdefault("overlap_ratio", leg["overlap_ratio"])
                if isinstance(leg.get("programs"), dict):
                    out["programs"].update(leg["programs"])
                flight = leg.get("flight")
                if isinstance(flight, dict):
                    for key in (
                        "step_ms_p50", "host_exposed_ms_p50",
                        "host_overhead_ms_p50",
                    ):
                        if flight.get(key) is not None:
                            metrics.setdefault(key, flight[key])
                    if flight.get("recompile_count") is not None:
                        metrics.setdefault(
                            "recompile_count", flight["recompile_count"]
                        )
                if leg.get("decode_host_fetches_per_chunk") is not None:
                    metrics.setdefault(
                        "decode_host_fetches_per_chunk",
                        leg["decode_host_fetches_per_chunk"],
                    )
        spec = detail.get("speculative")
        if isinstance(spec, dict):
            if spec.get("uplift") is not None:
                metrics["speculative_uplift"] = spec["uplift"]
            if spec.get("accepted_per_step") is not None:
                metrics["speculative_accepted_per_step"] = spec[
                    "accepted_per_step"
                ]
            # the engine's own speculation section (schema-2): rolling
            # measured uplift and the fused one-fetch-per-step ratio
            eng = spec.get("engine")
            if isinstance(eng, dict):
                if eng.get("uplift") is not None:
                    metrics["speculative_measured_uplift"] = eng["uplift"]
                steps = eng.get("steps") or eng.get("dispatches")
                if steps and eng.get("fetches") is not None:
                    metrics["speculative_fetches_per_step"] = round(
                        eng["fetches"] / steps, 4
                    )
        if detail.get("gateway_ttft_p50_s") is not None:
            metrics["gateway_ttft_p50_s"] = detail["gateway_ttft_p50_s"]
        prefix = detail.get("prefix_cache")
        if isinstance(prefix, dict) and prefix.get("speedup") is not None:
            metrics["prefix_cache_speedup"] = prefix["speedup"]
        # tiered-prefix-store warm phase (gateway_bench
        # run_warm_prefix_phase): warm/hydrated TTFT + cross-replica
        # hydrate-vs-recompute speedup
        warm = detail.get("prefix_warm")
        if isinstance(warm, dict):
            for key in (
                "prefix_warm_ttft_p50_s", "prefix_warm_ttft_p99_s",
                "prefix_hydrate_ttft_s", "prefix_hydrate_speedup",
            ):
                if warm.get(key) is not None:
                    metrics[key] = warm[key]
            _journey_metrics(warm.get("journey_segments"), metrics)
        # device-survival storm (gateway_bench run_oom_storm_phase):
        # shed/completion/shrink posture under an injected OOM burst
        storm = detail.get("oom_storm")
        partition = detail.get("partition_storm")
        if isinstance(partition, dict):
            for key in (
                "partition_storm_shed_rate",
                "partition_storm_completed_fraction",
                "partition_storm_fallbacks",
                "partition_storm_ttft_p99_s",
            ):
                if partition.get(key) is not None:
                    metrics[key] = float(partition[key])
        if isinstance(storm, dict):
            for key in (
                "oom_storm_shed_rate", "oom_storm_completed_fraction",
                "oom_storm_shrinks", "oom_storm_ttft_p50_s",
                "oom_storm_ttft_p99_s",
            ):
                if storm.get(key) is not None:
                    metrics[key] = storm[key]
        # multi-LoRA adapter phase (gateway_bench run_multi_lora_phase):
        # mixed-adapter TTFT quantiles, T0 hit ratio, eviction churn
        lora = detail.get("multi_lora")
        if isinstance(lora, dict):
            for key in (
                "multi_lora_ttft_p99_s", "multi_lora_t0_hit_ratio",
                "multi_lora_evictions", "multi_lora_hydrate_ttft_p99_s",
            ):
                if lora.get(key) is not None:
                    metrics[key] = float(lora[key])
            _journey_metrics(lora.get("journey_segments"), metrics)
        # streaming-delivery phase (gateway_bench run_stream_phase):
        # client-observed TBT, first-frame TTFB, stall count, and the
        # disconnect-cancellation reclaim fraction
        stream = detail.get("gateway_stream")
        if isinstance(stream, dict):
            for key in (
                "gateway_stream_tbt_p50_s", "gateway_stream_tbt_p99_s",
                "gateway_stream_stalls", "gateway_stream_ttfb_s",
                "gateway_stream_cancel_reclaim_fraction",
            ):
                if stream.get(key) is not None:
                    metrics[key] = float(stream[key])
        # analyzer self-stats (bench.py parent side)
        analyzer = detail.get("analyzer")
        if isinstance(analyzer, dict):
            if analyzer.get("analyzer_wall_s") is not None:
                metrics["analyzer_wall_s"] = analyzer["analyzer_wall_s"]
            if analyzer.get("suppressions") is not None:
                metrics["analyzer_suppressions"] = analyzer["suppressions"]
        _journey_metrics(detail.get("journey_segments"), metrics)
        for leg in detail.values():
            if isinstance(leg, dict):
                _journey_metrics(leg.get("journey_segments"), metrics)
        return out

    # bare gateway_bench output: journey segments ride the top level
    if isinstance(payload, dict):
        _journey_metrics(payload.get("journey_segments"), metrics)
    # /flight dump or bare rollup(s): merge windows across engines
    rollups: list[dict] = []
    _walk_flight_rollups(payload, rollups)
    for entry in rollups:
        summary = entry.get("summary") or entry
        window = summary.get("window") or summary
        for key in (
            "step_ms_p50", "host_exposed_ms_p50", "host_overhead_ms_p50",
            "overlap_ratio", "tok_s",
        ):
            if _first(window, key) is not None:
                metrics.setdefault(key, window[key])
        totals = summary.get("totals") or {}
        recompiles = _first(
            totals, "recompiles", default=entry.get("recompile_count")
        )
        if recompiles is not None:
            metrics.setdefault("recompile_count", recompiles)
        # attribution payloads riding in the dump
        for program in entry.get("programs") or []:
            if isinstance(program, dict) and program.get("program"):
                out["programs"][program["program"]] = program.get(
                    "dispatches", 0
                )
    return out


def diff_metrics(
    base: dict, new: dict, threshold: float = DEFAULT_THRESHOLD
) -> dict:
    """Compare two extractions. Returns ``regressions`` (beyond the
    noise band, in the declared worse direction), ``improvements``
    (beyond the band the other way — reported, never flagged), and
    ``notes`` (coverage/schema/census drift)."""
    regressions: list[dict] = []
    improvements: list[dict] = []
    notes: list[str] = []
    base_m, new_m = base["metrics"], new["metrics"]
    for metric, worse in METRICS.items():
        b, n = base_m.get(metric), new_m.get(metric)
        if b is None or n is None:
            if (b is None) != (n is None):
                notes.append(
                    f"{metric}: only in "
                    f"{'new' if b is None else 'base'} round (coverage "
                    f"drift, not compared)"
                )
            continue
        if not isinstance(b, (int, float)) or not isinstance(n, (int, float)):
            continue
        if b == 0:
            continue
        change = (n - b) / abs(b)
        entry = {
            "metric": metric,
            "base": b,
            "new": n,
            "change": round(change, 4),
        }
        if abs(change) < threshold:
            continue
        regressed = change > 0 if worse == "up" else change < 0
        (regressions if regressed else improvements).append(entry)
    if base.get("schema") != new.get("schema"):
        notes.append(
            f"schema drift: base {base.get('schema')!r} vs new "
            f"{new.get('schema')!r}"
        )
    bp, np_ = set(base.get("programs") or ()), set(new.get("programs") or ())
    if bp and np_ and bp != np_:
        gone, fresh = sorted(bp - np_), sorted(np_ - bp)
        notes.append(
            "program census changed"
            + (f"; dropped: {', '.join(gone[:4])}" if gone else "")
            + (f"; new: {', '.join(fresh[:4])}" if fresh else "")
            + " — read step-time shifts against the new variant set"
        )
    return {
        "regressions": regressions,
        "improvements": improvements,
        "notes": notes,
    }


def render(label_base: str, label_new: str, result: dict,
           threshold: float) -> str:
    lines = [f"== {label_base} -> {label_new} =="]
    for entry in result.get("gate", ()):
        lines.append(
            f"  !! GATE {entry['metric']}: {entry['base']} -> "
            f"{entry['new']} ({100 * entry['change']:+.1f}% past the "
            f"±{100 * entry['limit']:.0f}% SLO gate)"
        )
    for entry in result["regressions"]:
        lines.append(
            f"  !! REGRESSION {entry['metric']}: {entry['base']} -> "
            f"{entry['new']} ({100 * entry['change']:+.1f}%)"
        )
    for entry in result["improvements"]:
        lines.append(
            f"  improvement {entry['metric']}: {entry['base']} -> "
            f"{entry['new']} ({100 * entry['change']:+.1f}%)"
        )
    for note in result["notes"]:
        lines.append(f"  note: {note}")
    if not result["regressions"]:
        lines.append(
            f"  no regressions beyond ±{100 * threshold:.0f}% noise band"
        )
    return "\n".join(lines)


def diff_payloads(
    labeled: list[tuple[str, object]],
    threshold: float = DEFAULT_THRESHOLD,
    gate: bool = False,
) -> tuple[list[tuple[str, str, dict]], bool]:
    """Pairwise diffs over consecutive already-loaded payloads (label,
    parsed JSON), oldest first — the entry point for callers that hold
    the dumps in memory (engine_top's multi-dump ``--analyze`` loads
    each file once for decomposition and hands the payloads here).
    Returns the pair results and whether any regression was flagged.
    With ``gate=True`` each result additionally carries a ``gate`` list
    (:func:`gate_violations`), and a violation counts as a flagged
    regression — the SLO gate fails the run even inside the noise
    band."""
    extracted = [
        (label, extract_metrics(payload)) for label, payload in labeled
    ]
    results = []
    any_regression = False
    for (base_label, base), (new_label, new) in zip(extracted, extracted[1:]):
        result = diff_metrics(base, new, threshold)
        if gate:
            result["gate"] = gate_violations(
                base["metrics"], new["metrics"]
            )
            any_regression = any_regression or bool(result["gate"])
        any_regression = any_regression or bool(result["regressions"])
        results.append((base_label, new_label, result))
    return results, any_regression


def diff_files(
    paths: list[str],
    threshold: float = DEFAULT_THRESHOLD,
    gate: bool = False,
) -> tuple[list[tuple[str, str, dict]], bool]:
    """Pairwise diffs over consecutive files (sorted order is the
    caller's business — pass rounds oldest first). Returns the pair
    results and whether any regression was flagged."""
    labeled = []
    for path in paths:
        with open(path) as f:
            labeled.append((path, json.load(f)))
    return diff_payloads(labeled, threshold, gate=gate)


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        description="flag perf regressions between bench/flight rounds"
    )
    parser.add_argument(
        "files", nargs="+",
        help="two or more BENCH_r*.json records or saved /flight dumps, "
        "oldest first (consecutive pairs are compared)",
    )
    parser.add_argument(
        "--threshold", type=float, default=DEFAULT_THRESHOLD,
        help=f"relative noise band (default {DEFAULT_THRESHOLD})",
    )
    parser.add_argument(
        "--json", action="store_true", help="emit results as JSON"
    )
    parser.add_argument(
        "--gate", action="store_true",
        help="additionally judge gated metrics against their per-metric "
        "SLO thresholds (GATE_THRESHOLDS) and exit non-zero on any "
        "violation — the bench-verdict regression gate (first gated "
        "direction: the streaming TBT p99)",
    )
    args = parser.parse_args(argv)
    if len(args.files) < 2:
        parser.error("need at least two files to diff")
    try:
        results, any_regression = diff_files(
            args.files, args.threshold, gate=args.gate
        )
    except (OSError, ValueError) as e:
        print(f"perf_diff failed: {e}", file=sys.stderr)
        return 2
    if args.json:
        print(json.dumps(
            [
                {"base": b, "new": n, **result}
                for b, n, result in results
            ],
            indent=2,
        ))
    else:
        for base_path, new_path, result in results:
            print(render(base_path, new_path, result, args.threshold))
    return 1 if any_regression else 0


if __name__ == "__main__":
    raise SystemExit(main())
