"""Render the installable k8s manifests into ``deploy/k8s/``.

    python tools/render_deploy.py

The rendered YAML is CHECKED IN (parity: the reference ships ``helm/`` with
CRDs and values examples) so `kubectl apply -f deploy/k8s/` installs the
control plane, api-gateway, and operator without running any Python — the
generator exists so the manifests never drift from the Python factories
(CRDs come straight from ``langstream_tpu.k8s.crds.crd_manifests``).
"""

from __future__ import annotations

import sys
from pathlib import Path

import yaml

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))
OUT = REPO / "deploy" / "k8s"

NAMESPACE = "langstream-tpu"
IMAGE = "langstream-tpu/runtime:latest"


def deployment(name: str, command: list[str], env: list[dict], sa: str) -> dict:
    return {
        "apiVersion": "apps/v1",
        "kind": "Deployment",
        "metadata": {"name": name, "namespace": NAMESPACE},
        "spec": {
            "replicas": 1,
            "selector": {"matchLabels": {"app": name}},
            "template": {
                "metadata": {"labels": {"app": name}},
                "spec": {
                    "serviceAccountName": sa,
                    "containers": [
                        {
                            "name": name,
                            "image": IMAGE,
                            "command": command,
                            "env": env,
                            "ports": [{"containerPort": 8090 if "control" in name else 8091}],
                            "resources": {
                                "requests": {"cpu": "200m", "memory": "512Mi"}
                            },
                        }
                    ],
                },
            },
        },
    }


def service(name: str, port: int) -> dict:
    return {
        "apiVersion": "v1",
        "kind": "Service",
        "metadata": {"name": name, "namespace": NAMESPACE},
        "spec": {
            "selector": {"app": name},
            "ports": [{"port": port, "targetPort": port}],
        },
    }


def rbac() -> list[dict]:
    # least privilege: explicit verb lists (tenant namespaces are created
    # dynamically, so the grants must be cluster-scoped, but nothing here
    # needs wildcard verbs)
    crud = ["get", "list", "watch", "create", "update", "patch", "delete"]
    rules_control_plane = [
        {"apiGroups": ["langstream.tpu"], "resources": ["applications", "agents"],
         "verbs": crud},
        # status subresources are distinct RBAC resources; reconcilers and
        # the store write them (k8s/client.py update_status)
        {"apiGroups": ["langstream.tpu"],
         "resources": ["applications/status", "agents/status"],
         "verbs": ["get", "update", "patch"]},
        {"apiGroups": [""], "resources": ["secrets", "configmaps"],
         "verbs": crud},
        # tenant lifecycle: namespaces are created on tenant create,
        # re-applied on tenant update, and deleted on tenant delete
        {"apiGroups": [""], "resources": ["namespaces"], "verbs": crud},
    ]
    rules_operator = rules_control_plane + [
        {"apiGroups": ["apps"], "resources": ["statefulsets"], "verbs": crud},
        {"apiGroups": [""], "resources": ["services", "persistentvolumeclaims"],
         "verbs": crud},
        {"apiGroups": [""], "resources": ["pods"],
         "verbs": ["get", "list", "watch"]},
        {"apiGroups": ["batch"], "resources": ["jobs"], "verbs": crud},
    ]
    out = []
    for name, rules in (
        ("langstream-control-plane", rules_control_plane),
        ("langstream-operator", rules_operator),
    ):
        out += [
            {"apiVersion": "v1", "kind": "ServiceAccount",
             "metadata": {"name": name, "namespace": NAMESPACE}},
            {"apiVersion": "rbac.authorization.k8s.io/v1", "kind": "ClusterRole",
             "metadata": {"name": name}, "rules": rules},
            {"apiVersion": "rbac.authorization.k8s.io/v1",
             "kind": "ClusterRoleBinding",
             "metadata": {"name": name},
             "roleRef": {"apiGroup": "rbac.authorization.k8s.io",
                         "kind": "ClusterRole", "name": name},
             "subjects": [{"kind": "ServiceAccount", "name": name,
                           "namespace": NAMESPACE}]},
        ]
    return out


def main() -> None:
    from langstream_tpu.k8s.crds import crd_manifests

    OUT.mkdir(parents=True, exist_ok=True)

    def write(name: str, docs: list[dict]) -> None:
        (OUT / name).write_text(yaml.safe_dump_all(docs, sort_keys=False))
        print(f"wrote deploy/k8s/{name} ({len(docs)} documents)")

    write("00-namespace.yaml", [
        {"apiVersion": "v1", "kind": "Namespace",
         "metadata": {"name": NAMESPACE}},
    ])
    write("01-crds.yaml", crd_manifests())
    write("02-rbac.yaml", rbac())
    write("03-control-plane.yaml", [
        deployment(
            "langstream-control-plane",
            ["python", "-m", "langstream_tpu.controlplane"],
            [
                {"name": "LS_MODE", "value": "k8s"},
                {"name": "LS_PORT", "value": "8090"},
                {"name": "LS_RUNTIME_IMAGE", "value": IMAGE},
                # point at an in-cluster S3 (e.g. minio) or Azure blob store;
                # see values-example.yaml
                {"name": "LS_CODE_STORAGE", "valueFrom": {"configMapKeyRef": {
                    "name": "langstream-config", "key": "code-storage",
                    "optional": True}}},
                {"name": "LS_ADMIN_AUTH", "valueFrom": {"configMapKeyRef": {
                    "name": "langstream-config", "key": "admin-auth",
                    "optional": True}}},
            ],
            "langstream-control-plane",
        ),
        service("langstream-control-plane", 8090),
    ])
    write("04-api-gateway.yaml", [
        # the gateway needs NO kubernetes API access (it polls the control
        # plane over HTTP) and is the internet-facing component — its own
        # rule-less ServiceAccount keeps a compromise worthless
        {"apiVersion": "v1", "kind": "ServiceAccount",
         "metadata": {"name": "langstream-api-gateway",
                      "namespace": NAMESPACE},
         "automountServiceAccountToken": False},
        deployment(
            "langstream-api-gateway",
            ["python", "-m", "langstream_tpu.gateway"],
            [
                {"name": "LS_PORT", "value": "8091"},
                {"name": "LS_CONTROL_PLANE_URL",
                 "value": "http://langstream-control-plane:8090"},
                {"name": "LS_CONTROL_PLANE_TOKEN", "valueFrom": {
                    "secretKeyRef": {"name": "langstream-admin-token",
                                     "key": "token", "optional": True}}},
            ],
            "langstream-api-gateway",
        ),
        service("langstream-api-gateway", 8091),
    ])
    write("05-operator.yaml", [
        deployment(
            "langstream-operator",
            ["python", "-m", "langstream_tpu.k8s.operator"],
            [
                {"name": "LS_ACCELERATOR", "value": "v5e"},
            ],
            "langstream-operator",
        ),
    ])


if __name__ == "__main__":
    main()
