"""Render the installable k8s manifests into ``deploy/k8s/`` — and, with
``--helm``, an installable Helm chart into ``deploy/helm/langstream-tpu/``.

    python tools/render_deploy.py            # plain manifests (kubectl apply)
    python tools/render_deploy.py --helm     # Helm chart (helm install)

The rendered YAML is CHECKED IN (parity: the reference ships ``helm/`` with
CRDs and values examples; the chart proper lives in a separate repo per
``helm/README.md`` — here both live in-tree) so installation needs no
Python — the generator exists so the manifests never drift from the Python
factories (CRDs come straight from ``langstream_tpu.k8s.crds.crd_manifests``).

The chart is produced from the SAME documents as the plain manifests:
namespace/image/accelerator fields are swapped for ``{{ .Release.Namespace
}}`` / ``{{ .Values.* }}`` template expressions, CRDs go under ``crds/``
(Helm installs them before templates), and an optional ConfigMap template
carries ``codeStorage`` / ``adminAuth`` from values (the hand-created
ConfigMap of the kubectl path, see ``values-example.yaml``).
"""

from __future__ import annotations

import sys
from pathlib import Path

import yaml

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))
OUT = REPO / "deploy" / "k8s"
HELM_OUT = REPO / "deploy" / "helm" / "langstream-tpu"

NAMESPACE = "langstream-tpu"
IMAGE = "langstream-tpu/runtime:latest"
CHART_VERSION = "0.4.0"


def deployment(name: str, command: list[str], env: list[dict], sa: str) -> dict:
    return {
        "apiVersion": "apps/v1",
        "kind": "Deployment",
        "metadata": {"name": name, "namespace": NAMESPACE},
        "spec": {
            "replicas": 1,
            "selector": {"matchLabels": {"app": name}},
            "template": {
                "metadata": {"labels": {"app": name}},
                "spec": {
                    "serviceAccountName": sa,
                    "containers": [
                        {
                            "name": name,
                            "image": IMAGE,
                            "command": command,
                            "env": env,
                            "ports": [{"containerPort": 8090 if "control" in name else 8091}],
                            "resources": {
                                "requests": {"cpu": "200m", "memory": "512Mi"}
                            },
                        }
                    ],
                },
            },
        },
    }


def service(name: str, port: int) -> dict:
    return {
        "apiVersion": "v1",
        "kind": "Service",
        "metadata": {"name": name, "namespace": NAMESPACE},
        "spec": {
            "selector": {"app": name},
            "ports": [{"port": port, "targetPort": port}],
        },
    }


def rbac() -> list[dict]:
    # least privilege: explicit verb lists (tenant namespaces are created
    # dynamically, so the grants must be cluster-scoped, but nothing here
    # needs wildcard verbs)
    crud = ["get", "list", "watch", "create", "update", "patch", "delete"]
    rules_control_plane = [
        {"apiGroups": ["langstream.tpu"], "resources": ["applications", "agents"],
         "verbs": crud},
        # status subresources are distinct RBAC resources; reconcilers and
        # the store write them (k8s/client.py update_status)
        {"apiGroups": ["langstream.tpu"],
         "resources": ["applications/status", "agents/status"],
         "verbs": ["get", "update", "patch"]},
        {"apiGroups": [""], "resources": ["secrets", "configmaps"],
         "verbs": crud},
        # tenant lifecycle: namespaces are created on tenant create,
        # re-applied on tenant update, and deleted on tenant delete
        {"apiGroups": [""], "resources": ["namespaces"], "verbs": crud},
    ]
    rules_operator = rules_control_plane + [
        {"apiGroups": ["apps"], "resources": ["statefulsets"], "verbs": crud},
        {"apiGroups": [""], "resources": ["services", "persistentvolumeclaims"],
         "verbs": crud},
        {"apiGroups": [""], "resources": ["pods"],
         "verbs": ["get", "list", "watch"]},
        {"apiGroups": ["batch"], "resources": ["jobs"], "verbs": crud},
    ]
    out = []
    for name, rules in (
        ("langstream-control-plane", rules_control_plane),
        ("langstream-operator", rules_operator),
    ):
        out += [
            {"apiVersion": "v1", "kind": "ServiceAccount",
             "metadata": {"name": name, "namespace": NAMESPACE}},
            {"apiVersion": "rbac.authorization.k8s.io/v1", "kind": "ClusterRole",
             "metadata": {"name": name}, "rules": rules},
            {"apiVersion": "rbac.authorization.k8s.io/v1",
             "kind": "ClusterRoleBinding",
             "metadata": {"name": name},
             "roleRef": {"apiGroup": "rbac.authorization.k8s.io",
                         "kind": "ClusterRole", "name": name},
             "subjects": [{"kind": "ServiceAccount", "name": name,
                           "namespace": NAMESPACE}]},
        ]
    return out


def render_documents() -> dict[str, list[dict]]:
    """filename → manifest documents; one source of truth for both the
    plain-kubectl tree and the Helm chart."""
    from langstream_tpu.k8s.crds import crd_manifests

    docs: dict[str, list[dict]] = {}
    docs["00-namespace.yaml"] = [
        {"apiVersion": "v1", "kind": "Namespace",
         "metadata": {"name": NAMESPACE}},
    ]
    docs["01-crds.yaml"] = crd_manifests()
    docs["02-rbac.yaml"] = rbac()
    docs["03-control-plane.yaml"] = [
        deployment(
            "langstream-control-plane",
            ["python", "-m", "langstream_tpu.controlplane"],
            [
                {"name": "LS_MODE", "value": "k8s"},
                {"name": "LS_PORT", "value": "8090"},
                {"name": "LS_RUNTIME_IMAGE", "value": IMAGE},
                # point at an in-cluster S3 (e.g. minio) or Azure blob store;
                # see values-example.yaml
                {"name": "LS_CODE_STORAGE", "valueFrom": {"configMapKeyRef": {
                    "name": "langstream-config", "key": "code-storage",
                    "optional": True}}},
                {"name": "LS_ADMIN_AUTH", "valueFrom": {"configMapKeyRef": {
                    "name": "langstream-config", "key": "admin-auth",
                    "optional": True}}},
            ],
            "langstream-control-plane",
        ),
        service("langstream-control-plane", 8090),
    ]
    docs["04-api-gateway.yaml"] = [
        # the gateway needs NO kubernetes API access (it polls the control
        # plane over HTTP) and is the internet-facing component — its own
        # rule-less ServiceAccount keeps a compromise worthless
        {"apiVersion": "v1", "kind": "ServiceAccount",
         "metadata": {"name": "langstream-api-gateway",
                      "namespace": NAMESPACE},
         "automountServiceAccountToken": False},
        deployment(
            "langstream-api-gateway",
            ["python", "-m", "langstream_tpu.gateway"],
            [
                {"name": "LS_PORT", "value": "8091"},
                {"name": "LS_CONTROL_PLANE_URL",
                 "value": "http://langstream-control-plane:8090"},
                {"name": "LS_CONTROL_PLANE_TOKEN", "valueFrom": {
                    "secretKeyRef": {"name": "langstream-admin-token",
                                     "key": "token", "optional": True}}},
            ],
            "langstream-api-gateway",
        ),
        service("langstream-api-gateway", 8091),
    ]
    docs["05-operator.yaml"] = [
        deployment(
            "langstream-operator",
            ["python", "-m", "langstream_tpu.k8s.operator"],
            [
                {"name": "LS_ACCELERATOR", "value": "v5e"},
            ],
            "langstream-operator",
        ),
    ]
    return docs


def _rel(path: Path) -> Path:
    return path.relative_to(REPO) if path.is_relative_to(REPO) else path


def write_plain(out: Path) -> None:
    out.mkdir(parents=True, exist_ok=True)
    for name, docs in render_documents().items():
        (out / name).write_text(yaml.safe_dump_all(docs, sort_keys=False))
        print(f"wrote {_rel(out)}/{name} ({len(docs)} documents)")


_CONFIG_TEMPLATE = """\
{{- if .Values.codeStorage }}
apiVersion: v1
kind: ConfigMap
metadata:
  name: langstream-config
  namespace: {{ .Release.Namespace }}
data:
  code-storage: {{ .Values.codeStorage | toJson | quote }}
  {{- if .Values.adminAuth }}
  admin-auth: {{ .Values.adminAuth | toJson | quote }}
  {{- end }}
{{- end }}
"""

_NOTES = """\
langstream-tpu installed into namespace {{ .Release.Namespace }}.

Control plane:  http://langstream-control-plane.{{ .Release.Namespace }}:8090
API gateway:    ws://langstream-api-gateway.{{ .Release.Namespace }}:8091

Point the CLI at it:
  python -m langstream_tpu.cli profiles set default \\
      --web-service-url http://langstream-control-plane.{{ .Release.Namespace }}:8090

RBAC note: ClusterRole/ClusterRoleBinding names are fixed (tenant
namespaces are created dynamically, so grants are cluster-scoped) —
install one release per cluster.
"""


def _helm_template(doc_yaml: str) -> str:
    """Swap the concrete install-time choices for template expressions.
    Values are quoted YAML-safely because the replacements sit in scalar
    positions that were already plain strings."""
    out = doc_yaml.replace(f"namespace: {NAMESPACE}", "namespace: {{ .Release.Namespace }}")
    out = out.replace(f"image: {IMAGE}", "image: {{ .Values.image }}")
    # the control plane stamps LS_RUNTIME_IMAGE into every Agent CR — it
    # must follow .Values.image too, or agent pods pull the default image
    out = out.replace(f"value: {IMAGE}", "value: {{ .Values.image | quote }}")
    out = out.replace("value: v5e", "value: {{ .Values.accelerator | quote }}")
    return out


def write_helm(out: Path) -> None:
    templates = out / "templates"
    crds = out / "crds"
    templates.mkdir(parents=True, exist_ok=True)
    crds.mkdir(parents=True, exist_ok=True)

    (out / "Chart.yaml").write_text(yaml.safe_dump({
        "apiVersion": "v2",
        "name": "langstream-tpu",
        "description": "Event-driven LLM streaming platform with in-tree "
                       "TPU serving (control plane, api-gateway, operator)",
        "type": "application",
        "version": CHART_VERSION,
        "appVersion": CHART_VERSION,
    }, sort_keys=False))
    (out / "values.yaml").write_text(
        "# Install-time configuration. See deploy/k8s/values-example.yaml\n"
        "# for a worked codeStorage example.\n"
        + yaml.safe_dump({
            "image": IMAGE,
            "accelerator": "v5e",
            # JSON-able structures; null disables the ConfigMap template
            "codeStorage": None,
            "adminAuth": None,
        }, sort_keys=False)
    )

    for name, docs in render_documents().items():
        if name == "00-namespace.yaml":
            continue  # helm install --create-namespace owns this
        body = yaml.safe_dump_all(docs, sort_keys=False)
        if name == "01-crds.yaml":
            (crds / name).write_text(body)  # CRDs install pre-template, untemplated
        else:
            (templates / name).write_text(_helm_template(body))
    (templates / "06-config.yaml").write_text(_CONFIG_TEMPLATE)
    (templates / "NOTES.txt").write_text(_NOTES)
    print(f"wrote helm chart under {_rel(out)}/")


def main() -> None:
    import argparse

    ap = argparse.ArgumentParser()
    ap.add_argument("--helm", action="store_true",
                    help="render the Helm chart instead of plain manifests")
    ap.add_argument("--out", default=None,
                    help="override the output directory")
    args = ap.parse_args()
    if args.helm:
        write_helm(Path(args.out) if args.out else HELM_OUT)
    else:
        write_plain(Path(args.out) if args.out else OUT)


if __name__ == "__main__":
    main()
