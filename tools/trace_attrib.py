#!/usr/bin/env python3
"""trace attrib: per-op device-time buckets from a profiler capture.

The attribution ledger (``serving/attribution.py``) is *analytical*: it
predicts what each program should cost from model shape and pairs it
with measured dispatch time. When the model and the chip disagree — a
program whose achieved-vs-expected ratio is far off with no host-side
explanation — the post-mortem needs op-level device truth. This tool
parses a ``ProfilerHooks`` capture (``LS_TPU_PROFILE_DIR`` /
``/profile/start`` — ``jax.profiler`` writes Chrome-trace
``*.trace.json.gz`` files under ``plugins/profile/<run>/``) into
per-op device-time buckets:

    attention / mlp / collectives / copies / sampling / other

so "this decode program runs at 0.3× its roofline" decomposes into
"because 40% of its device time is layout copies", without TensorBoard
or Perfetto in the loop.

    python tools/trace_attrib.py /tmp/profile            # capture dir
    python tools/trace_attrib.py trace.json.gz --json    # one file
    python tools/trace_attrib.py trace.json --top 10

Zero dependencies (stdlib only). Classification is a keyword table over
XLA op names — fused ops bucket by their first matching keyword, in
table order (attention before mlp: an "attention" fusion full of dots
is attention). The table is a heuristic, printed with the output so a
surprising bucket is auditable.
"""

from __future__ import annotations

import argparse
import gzip
import json
import os
import re
import sys

#: bucket → name keywords, checked IN ORDER (first match wins). Op and
#: fusion names are lower-cased before matching.
BUCKET_KEYWORDS: tuple[tuple[str, tuple[str, ...]], ...] = (
    ("attention", (
        "attention", "flash", "paged", "softmax", "logits_qk", "qk",
        "masked_fill", "rope",
    )),
    ("collectives", (
        "all-reduce", "all_reduce", "allreduce",
        "all-gather", "all_gather", "allgather",
        "reduce-scatter", "reduce_scatter",
        "all-to-all", "all_to_all", "alltoall",
        "collective", "psum", "ppermute", "permute", "send", "recv",
    )),
    ("sampling", (
        "sort", "top-k", "top_k", "topk", "argmax", "arg_max", "rng",
        "random", "gumbel", "sample", "threefry", "iota",
    )),
    ("copies", (
        "copy", "transpose", "reshape", "broadcast", "concatenate",
        "slice", "gather", "scatter", "dynamic-update", "dynamic_update",
        "pad", "bitcast", "convert", "tuple", "infeed", "outfeed",
        "memset",
    )),
    ("mlp", (
        "dot", "einsum", "matmul", "convolution", "gemm", "mlp", "gate",
        "fusion", "cublas", "custom-call", "custom_call",
    )),
)

BUCKETS = tuple(name for name, _ in BUCKET_KEYWORDS) + ("other",)


def classify(name: str) -> str:
    lowered = name.lower()
    for bucket, keywords in BUCKET_KEYWORDS:
        if any(k in lowered for k in keywords):
            return bucket
    return "other"


def _load_trace(path: str) -> dict:
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rt", encoding="utf-8", errors="replace") as f:
        return json.load(f)


def find_trace_files(root: str) -> list[str]:
    """Trace files under a capture dir (``plugins/profile/<run>/…``), or
    the file itself when pointed at one directly."""
    if os.path.isfile(root):
        return [root]
    found: list[str] = []
    for dirpath, _dirnames, filenames in os.walk(root):
        for filename in filenames:
            if filename.endswith((".trace.json.gz", "trace.json.gz",
                                  ".trace.json", "trace.json")):
                found.append(os.path.join(dirpath, filename))
    return sorted(found)


def _device_pids(trace: dict) -> set[int]:
    """pids whose process_name metadata looks like a device lane (TPU /
    GPU / XLA device streams). Empty when the trace carries no such
    metadata — the caller then buckets every complete event (CPU-only
    captures still decompose usefully)."""
    pids: set[int] = set()
    for event in trace.get("traceEvents", []):
        if event.get("ph") == "M" and event.get("name") == "process_name":
            pname = str((event.get("args") or {}).get("name", "")).lower()
            if re.search(r"tpu|gpu|xla|/device:|device:|accelerator", pname):
                pids.add(event.get("pid"))
    return pids


def bucket_events(trace: dict) -> dict:
    """Per-bucket totals over one trace's complete (``ph: X``) events.
    Durations are Chrome-trace microseconds; output is milliseconds."""
    device_pids = _device_pids(trace)
    totals: dict[str, float] = {b: 0.0 for b in BUCKETS}
    counts: dict[str, int] = {b: 0 for b in BUCKETS}
    by_op: dict[str, dict[str, float]] = {b: {} for b in BUCKETS}
    for event in trace.get("traceEvents", []):
        if event.get("ph") != "X":
            continue
        if device_pids and event.get("pid") not in device_pids:
            continue
        dur_us = event.get("dur")
        name = event.get("name")
        if not name or not isinstance(dur_us, (int, float)):
            continue
        bucket = classify(name)
        ms = dur_us / 1000.0
        totals[bucket] += ms
        counts[bucket] += 1
        by_op[bucket][name] = by_op[bucket].get(name, 0.0) + ms
    return {"totals_ms": totals, "counts": counts, "by_op": by_op}


def merge(parts: list[dict]) -> dict:
    out = {
        "totals_ms": {b: 0.0 for b in BUCKETS},
        "counts": {b: 0 for b in BUCKETS},
        "by_op": {b: {} for b in BUCKETS},
    }
    for part in parts:
        for b in BUCKETS:
            out["totals_ms"][b] += part["totals_ms"][b]
            out["counts"][b] += part["counts"][b]
            for op, ms in part["by_op"][b].items():
                out["by_op"][b][op] = out["by_op"][b].get(op, 0.0) + ms
    return out


def report(agg: dict, top: int = 5) -> dict:
    """The serializable report: per-bucket device ms, share, event
    count, and the top ops by time."""
    total_ms = sum(agg["totals_ms"].values())
    buckets = {}
    for bucket in BUCKETS:
        ms = agg["totals_ms"][bucket]
        ops = sorted(
            agg["by_op"][bucket].items(), key=lambda kv: -kv[1]
        )[:top]
        buckets[bucket] = {
            "device_ms": round(ms, 3),
            "share": round(ms / total_ms, 4) if total_ms else 0.0,
            "events": agg["counts"][bucket],
            "top_ops": [
                {"name": op, "device_ms": round(op_ms, 3)}
                for op, op_ms in ops
            ],
        }
    return {"total_device_ms": round(total_ms, 3), "buckets": buckets}


def render(rep: dict) -> str:
    lines = [f"device time {rep['total_device_ms']:.1f}ms by op bucket:"]
    ranked = sorted(
        rep["buckets"].items(), key=lambda kv: -kv[1]["device_ms"]
    )
    for bucket, info in ranked:
        if not info["events"]:
            continue
        bar = "█" * int(round(info["share"] * 32))
        lines.append(
            f"  {bucket:12s} {info['device_ms']:10.1f}ms "
            f"{100 * info['share']:5.1f}%  {bar}"
        )
        for op in info["top_ops"][:3]:
            lines.append(
                f"               {op['name'][:48]:48s} "
                f"{op['device_ms']:.1f}ms"
            )
    return "\n".join(lines)


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        description="bucket a jax.profiler capture into per-op device time"
    )
    parser.add_argument(
        "path",
        help="ProfilerHooks capture dir (LS_TPU_PROFILE_DIR) or a "
        "trace.json[.gz] file",
    )
    parser.add_argument(
        "--json", action="store_true", help="emit the report as JSON"
    )
    parser.add_argument(
        "--top", type=int, default=5, help="top ops per bucket (default 5)"
    )
    args = parser.parse_args(argv)

    files = find_trace_files(args.path)
    if not files:
        print(f"no trace files under {args.path!r} (expected "
              f"*.trace.json[.gz] — is LS_TPU_PROFILE_DIR pointed at a "
              f"finished capture?)", file=sys.stderr)
        return 2
    parts = []
    for path in files:
        try:
            parts.append(bucket_events(_load_trace(path)))
        except (OSError, ValueError) as e:
            print(f"skipping {path}: {e}", file=sys.stderr)
    if not parts:
        print("no parseable trace files", file=sys.stderr)
        return 2
    rep = report(merge(parts), top=args.top)
    rep["files"] = files
    if args.json:
        print(json.dumps(rep, indent=2))
    else:
        print(render(rep))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
